"""SimService: the batched message plane as a long-lived service.

PR 10 built the engine room — ``engine.run_batch_until_coverage``
advances B in-flight floods per compiled round, lane exhaustion is the
designed backpressure signal, ``BatchFlood.admit``/``retire`` are the
staggered-admission seam — but nothing *served* it: the north-star
"heavy traffic from millions of users" (ROADMAP item 2) needs a
front-end that owns queueing, admission pacing, quotas, load shedding
and crash recovery. This module is that front-end, composing four
existing planes into one stateful process:

- **request plane** — :meth:`SimService.submit` /
  :meth:`~SimService.poll` / :meth:`~SimService.cancel` plus the
  blocking :meth:`~SimService.wait` / :meth:`~SimService.stream` APIs;
  the same surface rides the telemetry httpd as ``/submit``,
  ``/poll/<ticket>``, ``/cancel/<ticket>``, ``/stats`` next to
  ``/metrics``/``/history``/``/trace`` (``MetricsServer(service=...)``);
- **admission control** — a driver loop (:meth:`~SimService.tick`, run
  by a background thread or driven synchronously for deterministic
  tests) that paces ``BatchFlood.admit`` off the live active-lane count
  (the host-side twin of the ``sim_batch_active_lanes`` gauge) and the
  engine's observed completion-rounds percentiles (AIMD: a p99 past
  ``slo_rounds`` halves the per-tick admit budget, a healthy tick grows
  it back), runs the batch loop in ``chunk_rounds``-round chunks,
  harvests completed lanes back into a bounded FIFO of results, and
  load-sheds with a STRUCTURED reject (:class:`QueueFull` /
  :class:`QuotaExceeded`, counted into ``serve_rejected_total{reason}``)
  instead of erroring when lanes and queue exhaust;
- **crash tolerance** — the supervise-plane patterns over the donatable
  :class:`~p2pnetwork_tpu.models.messagebatch.MessageBatch` pytree:
  chunk keys are ``fold_in(base_key, round + 1)`` so resumed chunks walk
  the identical RNG/boundary schedule, the batch checkpoints into a
  :class:`~p2pnetwork_tpu.supervise.store.CheckpointStore` at tick
  boundaries with the control-plane ticket table in an atomically
  rename-published sidecar (``service_state.json``, referencing the
  exact checkpoint entry it describes), and a mid-flight kill
  (:class:`~p2pnetwork_tpu.supervise.runner.Preempted` via
  :meth:`~SimService.arm_preemption`, or a real SIGKILL) resumes with
  zero lost admitted lanes and per-lane results bit-identical to an
  uninterrupted run (tests/test_serve.py pins it);
- **determinism** — every control decision is a function of (tick,
  round, queue order, seed): quota buckets refill per tick, not per
  wall-second; ticket ids are a persisted counter; records store ticks
  and rounds, never wall timestamps (wall-clock latency lives only in
  the ``serve_latency_seconds`` histogram) — so a seeded traffic replay
  (serve/traffic.py) produces byte-identical per-ticket summaries.

Threading: control-plane state (tickets, queue, quotas, counters) is
guarded by one condition; the device-side batch is confined to the
single driver (whoever calls :meth:`~SimService.tick` — the background
thread in production, the test/bench harness in deterministic mode).
All service threads go through the concurrency seam, so graftrace can
explore submit/poll/driver interleavings (the ``serve_admit_storm``
scenario in the race battery).
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import numpy as np

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.models.messagebatch import BatchFlood
from p2pnetwork_tpu.serve.journal import Journal
from p2pnetwork_tpu.serve.journal import clear_segments as _clear_journal
from p2pnetwork_tpu.sim import checkpoint as ckpt
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as graph_mod
from p2pnetwork_tpu.supervise.runner import Preempted
from p2pnetwork_tpu.supervise.store import (CheckpointStore,
                                             atomic_write_json)
from p2pnetwork_tpu.supervise.watchdog import Watchdog
from p2pnetwork_tpu.telemetry import spans

__all__ = [
    "SimService", "Rejected", "QueueFull", "QuotaExceeded",
    "MemoryBudgetExceeded", "DurabilityLost", "FencedEpoch",
    "ServiceClosed", "GraphMismatch", "TERMINAL_STATES", "TICK_PHASES",
    "ticket_trace",
]

_SIDECAR = "service_state.json"

#: Ticket states a record never leaves.
TERMINAL_STATES = frozenset({"done", "cancelled", "timeout"})

#: Submit→completion latency buckets (rounds, queue wait included):
#: floods complete in O(diameter) rounds, queue wait adds chunk-sized
#: steps, so geometric 1..4096 covers both.
_LATENCY_ROUND_BUCKETS = telemetry.exponential_buckets(1.0, 2.0, 13)

#: graftsight tick-phase profiler: the driver phases every tick walks,
#: in execution order (ISSUE/ROADMAP naming: mutate — queued graph
#: deltas/growth applied atomically between chunks — then retire,
#: admit-marshal, device-dispatch, harvest, checkpoint).
TICK_PHASES = ("mutate", "retire", "admit", "dispatch", "harvest",
               "checkpoint")

#: Tick-phase histogram buckets: CPU-tick phases run ~10µs..10s.
_PHASE_SECOND_BUCKETS = telemetry.exponential_buckets(1e-5, 2.0, 20)


def ticket_trace(ticket: str) -> str:
    """The ticket's logical trace id (graftsight correlation): derived
    from the ticket id alone — deterministic, stable across replays —
    so ``/trace?trace_id=tkt-<ticket>`` exports one ticket's
    submit→admit→chunk→fault→heal→complete lifecycle."""
    return f"tkt-{ticket}"


def _delta_fields(delta: "graph_mod.GraphDelta") -> dict:
    """A GraphDelta as JSON-able journal fields (directed form — the
    stored arrays already carry both directions of an undirected
    build), inverted by :func:`_delta_from_fields` at replay."""
    return {
        "add_s": np.asarray(delta.add_senders).tolist(),
        "add_r": np.asarray(delta.add_receivers).tolist(),
        "add_w": (None if delta.add_weights is None
                  else np.asarray(delta.add_weights).tolist()),
        "rem_s": np.asarray(delta.remove_senders).tolist(),
        "rem_r": np.asarray(delta.remove_receivers).tolist(),
    }


def _delta_from_fields(rec: dict) -> "graph_mod.GraphDelta":
    return graph_mod.GraphDelta(
        add_senders=rec.get("add_s"), add_receivers=rec.get("add_r"),
        add_weights=rec.get("add_w"),
        remove_senders=rec.get("rem_s"),
        remove_receivers=rec.get("rem_r"))


class _PhaseClock:
    """Per-tick wall breakdown of the serve driver into the
    :data:`TICK_PHASES`. Always measures (``time.perf_counter`` deltas
    — a handful of clock reads per tick); additionally emits a
    ``serve_tick`` span with nested per-phase child spans when a tracer
    is installed. Wall times feed metrics/spans ONLY — never ticket
    records — so the serving plane's determinism contract holds with
    the profiler permanently on."""

    __slots__ = ("phases", "_t0", "_name", "_tracer", "_tick_sid", "_sid")

    def __init__(self, tracer):
        self._tracer = tracer
        self._tick_sid = tracer.begin("serve_tick") \
            if tracer is not None else None
        self._sid = None
        self._name: Optional[str] = None
        self.phases: Dict[str, float] = {}
        self._t0 = time.perf_counter()

    def _close_phase(self, now: float) -> None:
        if self._name is not None:
            self.phases[self._name] = (
                self.phases.get(self._name, 0.0) + (now - self._t0))
        if self._sid is not None:
            self._tracer.end(self._sid)
            self._sid = None

    def enter(self, name: str) -> None:
        now = time.perf_counter()
        self._close_phase(now)
        if self._tracer is not None:
            self._sid = self._tracer.begin(f"tick_{name}",
                                           parent=self._tick_sid)
        self._name, self._t0 = name, now

    def done(self, tick: int) -> Dict[str, float]:
        self._close_phase(time.perf_counter())
        self._name = None
        if self._tick_sid is not None:
            self._tracer.end(self._tick_sid)
            self._tracer.point(
                "tick_phases", parent=self._tick_sid, tick=tick,
                **{ph: self.phases.get(ph, 0.0) for ph in TICK_PHASES})
        return self.phases


class Rejected(RuntimeError):
    """Structured load-shed: the service refused an admission and says
    why, with the numbers the client needs to back off. Subclasses pin
    the reason; :meth:`to_dict` is the HTTP 429 payload."""

    reason = "rejected"

    def __init__(self, message: str, **details):
        self.details = dict(details)
        super().__init__(message)

    def to_dict(self) -> dict:
        return {"error": "rejected", "reason": self.reason, **self.details}


class QueueFull(Rejected):
    """The bounded submit FIFO is at ``queue_depth`` — the surfaced form
    of lane backpressure (the queue only builds while admission runs
    behind arrivals); carries the occupancy numbers to back off on."""

    reason = "queue_full"


class QuotaExceeded(Rejected):
    """The tenant's token bucket is empty this tick."""

    reason = "quota"


class MemoryBudgetExceeded(Rejected):
    """The graftmem capacity plan prices this admission (or growth) past
    the service's stated ``hbm_budget_bytes`` — refused up front with
    the planned numbers, never an OOM mid-tick. The plan comes from the
    checked-in ``membudgets.json`` capacity coefficients
    (analysis/ir/capacity.py), so the check is pure host arithmetic."""

    reason = "memory_budget"


class DurabilityLost(Rejected):
    """The write-ahead journal can no longer append (disk full, I/O
    error): the service flips to a LOUD shedding mode instead of
    silently accepting work it cannot make durable. Every subsequent
    submit/grow/apply_delta sheds with this reason (``503`` over HTTP,
    ``serve_rejected_total{reason="durability"}``) until a new service
    is constructed on a healthy volume — the trail up to the failure is
    intact and resumes normally. Sticky by design: a journal whose tail
    may be torn must not interleave fresh records after the tear."""

    reason = "durability"


class FencedEpoch(RuntimeError):
    """A demoted (zombie) primary tried to publish against a trail a
    newer epoch owns: :meth:`SimService.checkpoint` found a sidecar
    fencing token above its own. The publish was refused BEFORE
    touching the trail — split-brain is impossible by construction
    (promotion bumps the epoch and publishes the token first; any
    late writer then fails this check). Carries ``ours`` (the zombie's
    epoch) and ``current`` (the token in the sidecar)."""

    def __init__(self, message: str, *, ours: int, current: int):
        self.ours = int(ours)
        self.current = int(current)
        super().__init__(message)


class ServiceClosed(RuntimeError):
    """The service was closed (or its driver died); no more admissions."""


class GraphMismatch(ValueError):
    """The checkpoint trail records a different overlay than the graph
    this service was constructed with.

    The sidecar embeds a layout fingerprint (sim/layoutcache.py source
    digest folded with the graph's node/edge counts and edge-content
    hash), so a trail from overlay A can no longer resume "successfully"
    against overlay B just because the array shapes happen to agree.
    Raised WITHOUT touching the trail — the tickets in it are real;
    reconstruct with the right graph, or pass ``resume=False`` to
    deliberately discard them. Growth steps recorded in the sidecar are
    the sanctioned exception: a trail whose graph grew mid-service
    resumes from the pre-growth construction by replaying those steps.
    """

    def __init__(self, message: str, *, expected: Optional[str] = None,
                 got: Optional[str] = None, directory: str = ""):
        self.expected = expected
        self.got = got
        self.directory = directory
        super().__init__(message)


class SimService:
    """Simulation-as-a-service over ``engine.run_batch_until_coverage``.

    Parameters
    ----------
    graph, protocol:
        The graph to serve broadcasts on and the batched protocol
        (default :class:`~p2pnetwork_tpu.models.messagebatch.BatchFlood`).
    capacity:
        Lane capacity of the batch (rounded up to a whole 32-lane word —
        the real capacity is ``service.capacity``).
    queue_depth:
        Strict bound of the submit FIFO: a submit arriving with the
        queue at this depth is shed with :class:`QueueFull`. The queue
        drains only at tick boundaries, so it builds exactly when
        admission (lanes + pacing) runs behind arrivals — and
        ``queue_depth=0`` sheds every submit (a deliberate
        drain/maintenance mode; the smallest useful depth is 1).
    chunk_rounds:
        Engine rounds per driver tick (one compiled dispatch); smaller
        chunks mean finer admission/checkpoint granularity.
    max_ticket_rounds:
        A lane still unfinished after this many applied rounds is cut
        off: its ticket ends ``"timeout"`` (disconnected sources would
        otherwise hold a lane forever).
    seed:
        Base PRNG seed; chunk keys are ``fold_in(key(seed), round + 1)``
        (the supervise-plane schedule, so resume re-walks it).
    store / resume / checkpoint_every_ticks / retain:
        Crash tolerance: a :class:`CheckpointStore` (or directory path)
        the driver checkpoints the batch into every
        ``checkpoint_every_ticks`` ticks, with the ticket table in an
        atomic sidecar. ``resume=True`` (default) restores the newest
        consistent (checkpoint, sidecar) pair at construction;
        ``resume=False`` clears any previous trail.
    journal / journal_fsync:
        The graftdur sub-boundary durability plane (serve/journal.py):
        a write-ahead journal of every admission-plane intent in the
        store directory, appended BEFORE the intent is acknowledged, so
        a SIGKILL between checkpoint boundaries loses no acknowledged
        submit — resume restores the pair, then replays the journal
        suffix (:meth:`replay_next` / the drives' positional
        consumption) with the SAME ticket ids and bit-identical
        results. ``journal=None`` (default) enables it whenever a store
        is configured; ``False`` keeps the boundary-granular legacy
        semantics; ``True`` without a store is an error.
        ``journal_fsync`` is the power-loss policy knob
        (:data:`~p2pnetwork_tpu.serve.journal.FSYNC_POLICIES`:
        ``"record"`` / ``"tick"`` default / ``"off"``). An append
        failure flips the service into :class:`DurabilityLost`
        shedding — loud degradation, never silent un-journaled work.
    epoch:
        Fencing token for hot-standby failover. ``None`` (default)
        adopts the trail's epoch on resume (0 fresh); an explicit int
        pins it — :meth:`~p2pnetwork_tpu.serve.standby.Standby.promote`
        passes ``observed + 1`` so the promoted service's first
        checkpoint publishes a token every zombie-primary publish then
        fails against (:class:`FencedEpoch`).
    quotas:
        Per-tenant token buckets: ``{tenant: (refill_per_tick, burst)}``.
        Unlisted tenants are unlimited. Buckets refill at tick
        boundaries (deterministic), not per wall-second.
    max_active_lanes / slo_rounds:
        Admission pacing. ``max_active_lanes`` caps concurrently running
        lanes (default: full capacity). ``slo_rounds`` arms the AIMD
        controller: a chunk whose completion-rounds p99 exceeds it
        halves the per-tick admit budget; a healthy chunk adds
        ``capacity/16`` back (floor 1, ceiling the active-lane cap).
    done_retention:
        Terminal ticket records kept pollable (oldest evicted past the
        bound, so a long-lived service's table — and its sidecar — stay
        bounded).
    record_seen_hash:
        When True, each completed ticket's summary carries a sha256 of
        its lane's packed ``seen`` bits — the bit-identity witness the
        chaos-soak comparison uses (costs one host pull of the packed
        words per harvesting tick; off by default).
    heal:
        A :class:`~p2pnetwork_tpu.supervise.heal.RetryPolicy` (graftquake
        self-healing): the tick's engine chunk runs under a
        :class:`~p2pnetwork_tpu.supervise.heal.Healer` — undonated input
        retained as the rollback state, end-of-chunk integrity checks
        (template audit + batch-plane monotonicity), and policy-routed
        retry on detected faults (injected chip preemptions, wedged
        dispatches, integrity violations). A healed retry re-dispatches
        the SAME chunk key against the retained input, so recovered
        ticks are bit-identical to undisturbed ones and no admitted
        lane is lost. Costs one extra live batch copy (the retained
        input) plus one host pull of the carry per tick for the checks;
        ``None`` (default) keeps the donating fast path.
    slo:
        A graftsight :class:`~p2pnetwork_tpu.telemetry.slo.SLOEngine`
        (or ``None``). The driver feeds it per-ticket completion rounds
        and wall latency, per-submission shed flags and per-dispatch
        heal flags, and evaluates it once per tick; a firing objective
        with ``admission_signal=True`` halves the admit budget that
        tick (multiplicative decrease on sustained burn — the explicit
        SLO signal next to the ``slo_rounds`` percentile rule). Only
        deterministic observation streams may carry the signal, so
        seeded replays stay byte-identical.
    deadline_s / on_stall:
        Optional supervise-plane watchdog over driver ticks (heartbeat
        per tick; see supervise/watchdog.py for the stall modes).
    idle_wait_s:
        Background-driver poll interval while idle.
    """

    def __init__(self, graph, protocol: Optional[BatchFlood] = None, *,
                 capacity: int = 64, queue_depth: int = 256,
                 chunk_rounds: int = 16, max_ticket_rounds: int = 1024,
                 seed: int = 0,
                 store: Union[CheckpointStore, str, None] = None,
                 resume: bool = True, checkpoint_every_ticks: int = 1,
                 retain: int = 3,
                 journal: Optional[bool] = None,
                 journal_fsync: str = "tick",
                 epoch: Optional[int] = None,
                 quotas: Optional[Dict[str, Tuple[float, float]]] = None,
                 max_active_lanes: Optional[int] = None,
                 slo_rounds: Optional[float] = None,
                 done_retention: int = 4096,
                 record_seen_hash: bool = False,
                 heal=None,
                 slo=None,
                 deadline_s: Optional[float] = None,
                 on_stall: Union[str, Callable] = "raise",
                 idle_wait_s: float = 0.05,
                 hbm_budget_bytes: Optional[float] = None,
                 registry: Optional[telemetry.Registry] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if queue_depth < 0:
            raise ValueError("queue_depth must be >= 0")
        if chunk_rounds < 1:
            raise ValueError("chunk_rounds must be >= 1")
        if checkpoint_every_ticks < 1:
            raise ValueError("checkpoint_every_ticks must be >= 1")
        if done_retention < 1:
            raise ValueError("done_retention must be >= 1")
        self.graph = graph
        self._protocol = protocol if protocol is not None else BatchFlood()
        self._batch = self._protocol.empty(graph, capacity)
        #: Real lane capacity (requested, rounded up to a whole word).
        self.capacity = self._batch.capacity
        self.queue_depth = int(queue_depth)
        self.chunk_rounds = int(chunk_rounds)
        self.max_ticket_rounds = int(max_ticket_rounds)
        self.checkpoint_every_ticks = int(checkpoint_every_ticks)
        self.done_retention = int(done_retention)
        self.seed = int(seed)
        self._base_key = jax.random.key(self.seed)
        self._n_live = int(np.sum(np.asarray(graph.node_mask)))
        self._quotas = {str(t): (float(r), float(b))
                        for t, (r, b) in (quotas or {}).items()}
        for t, (r, b) in self._quotas.items():
            if r < 0 or b <= 0:
                raise ValueError(f"quota for {t!r} needs rate >= 0, burst > 0")
        # `is not None`, not truthiness: max_active_lanes=0 must be a
        # loud error, not a silent full-capacity default, and
        # slo_rounds=0.0 (the strictest possible SLO) must not silently
        # DISABLE pacing.
        if max_active_lanes is not None:
            max_active_lanes = int(max_active_lanes)
            if max_active_lanes < 1:
                raise ValueError("max_active_lanes must be >= 1 "
                                 "(use close() or quotas to pause intake)")
            self._target_active = min(max_active_lanes, self.capacity)
        else:
            self._target_active = self.capacity
        if slo_rounds is not None:
            slo_rounds = float(slo_rounds)
            if slo_rounds <= 0:
                raise ValueError("slo_rounds must be > 0 (None disables "
                                 "the AIMD controller)")
        self.slo_rounds = slo_rounds
        # Capacity-plan admission gate (graftmem): price the serving
        # program's per-chip footprint from the checked-in closed-form
        # coefficients, and refuse submits/grows that would plan past
        # the stated budget — the typed-429 alternative to an OOM
        # mid-tick. `is not None` again: 0 must be a loud error.
        if hbm_budget_bytes is not None:
            hbm_budget_bytes = float(hbm_budget_bytes)
            if hbm_budget_bytes <= 0:
                raise ValueError("hbm_budget_bytes must be > 0 (None "
                                 "disables the memory-budget gate)")
        self.hbm_budget_bytes = hbm_budget_bytes
        self._cap_model: Optional[dict] = None
        if hbm_budget_bytes is not None:
            from p2pnetwork_tpu.analysis.ir import memory as _graftmem

            # Loaded once — the admission path must stay pure host
            # arithmetic, not a JSON read per submit.
            self._cap_model = _graftmem.load_membudgets().get(
                "capacity_model")
            planned = self._planned_footprint_bytes(graph.n_nodes_padded)
            if planned is None:
                raise ValueError(
                    "hbm_budget_bytes is set but no capacity model is "
                    "available (membudgets.json lacks `capacity_model`) "
                    "— bless one with `graftaudit --write-membudgets` or "
                    "drop the knob")
            if planned > hbm_budget_bytes:
                # Construction over budget is operator error, not load —
                # a shed here would reject every submit forever.
                raise ValueError(
                    f"graph plans {planned} bytes/chip at construction, "
                    f"over hbm_budget_bytes={int(hbm_budget_bytes)} — "
                    "shard the overlay or raise the budget")
        self._record_seen_hash = bool(record_seen_hash)
        self.idle_wait_s = float(idle_wait_s)
        self.deadline_s = deadline_s
        self.on_stall = on_stall
        self._registry = registry
        #: graftsight SLO engine (telemetry/slo.py) or None. The driver
        #: feeds it per-ticket completion rounds/wall, per-submission
        #: shed flags and per-dispatch heal flags, evaluates it once per
        #: tick, and treats a firing admission-signal objective as an
        #: explicit multiplicative-decrease signal alongside the AIMD
        #: percentile rule. Evaluation is a pure function of
        #: deterministic feeds, so seeded replays stay byte-identical.
        self._slo = slo
        self._healer = None
        if heal is not None:
            from p2pnetwork_tpu.supervise.heal import Healer

            # Template from the empty batch: every chunk's harvested
            # carry must keep these exact shapes/dtypes (and finite
            # floats — MessageBatch carries none, so the audit is pure
            # structure here).
            template = jax.tree_util.tree_map(
                lambda x: np.zeros(x.shape, x.dtype), self._batch)
            self._healer = Healer(heal, template=template, monotonic=True,
                                  registry=registry)

        # ---- control plane (everything below _cond is guarded by it) --
        self._cond = concurrency.condition()
        self._tickets: Dict[str, dict] = {}
        self._queue: List[str] = []          # pending ticket ids, FIFO
        self._lane_ticket: Dict[int, str] = {}   # running lanes only
        self._cancel_lanes: List[int] = []   # cancelled mid-flight lanes
        self._done_order: List[str] = []     # terminal tids, oldest first
        self._buckets: Dict[str, float] = {
            t: b for t, (_, b) in self._quotas.items()}
        self._admit_budget = self._target_active
        self._round = 0        # cumulative engine rounds
        self._tick = 0         # completed driver ticks
        self._next_ticket = 0
        self._messages = 0     # cumulative exact message total
        self._latencies: List[float] = []   # rolling completion rounds
        self._counts = {"submitted": 0, "completed": 0, "cancelled": 0,
                        "rejected": 0, "timeout": 0, "mutations": 0}
        #: Queued live-mutation plane (graftchurn): (kind, payload, seq)
        #: triples — ("delta", GraphDelta, seq) / ("grow", n_new_nodes,
        #: seq), the seq being the journal record that acknowledged the
        #: intent (None unjournaled) — drained atomically by the
        #: driver's mutate tick phase.
        self._mutations: List[Tuple[str, Any, Optional[int]]] = []
        self._submit_walls: Dict[str, float] = {}
        # ---- graftdur durability plane (lock-guarded like the rest) --
        #: Why the journal refuses appends, or None while durable. Sticky:
        #: every admission sheds DurabilityLost until reconstruction.
        self._durability_lost: Optional[str] = None
        #: Journal records past the last published pair, awaiting replay
        #: (seq-ordered; drives consume positionally, tick()'s mutate
        #: phase is the fallback).
        self._replay_queue: List[dict] = []
        #: Last journal seqno appended AND acknowledged by this service.
        self._j_acked = 0
        #: Seqnos of journaled grow/delta intents still queued in
        #: _mutations (unapplied): the published cover must stay BELOW
        #: them or compaction would eat intents nothing has applied yet.
        self._j_pending_mut: List[int] = []
        #: Anything the sidecar records changed since the last published
        #: pair — gates checkpointing so an IDLE background driver
        #: (ticking every idle_wait_s for quota refill) does not
        #: re-serialize the full batch 20x a second forever.
        self._dirty = False
        self._closed = False
        self._driver_error: Optional[str] = None
        self._preempt_at: Optional[int] = None
        #: Failover fencing epoch (graftdur): published in the sidecar,
        #: checked before every publish (_check_fence). Pinned when the
        #: caller passed one; adopted from the trail otherwise.
        if epoch is not None:
            epoch = int(epoch)
            if epoch < 0:
                raise ValueError("epoch must be >= 0")
        self._epoch = 0 if epoch is None else epoch
        self._epoch_pinned = epoch is not None

        # ---- driver-confined (only the tick() caller touches these) ---
        self._retire_ready: List[int] = []   # harvested lanes to recycle
        self._thread: Optional[Any] = None
        self._watchdog: Optional[Watchdog] = None
        #: Crash-seam hooks (chaos/crashstorm.py): called as fn(tick) at
        #: the mid-tick point (between dispatch and harvest) and during
        #: the sidecar publish (between store entry and sidecar rename).
        #: Plain attributes — installing one is a test/chaos action.
        self._tick_fault: Optional[Callable[[int], None]] = None
        self._publish_fault: Optional[Callable[[int], None]] = None
        #: Growth steps applied this service lifetime (sidecar-recorded:
        #: the sanctioned resume path replays them onto the pre-growth
        #: construction). Driver-confined, like the graph they describe.
        self._growth_history: List[dict] = []
        #: Whether the served graph's delta-donate targets (degrees,
        #: neighbor-table rows) are buffers this service owns outright.
        #: The constructor graph is caller-owned — and a no-repad
        #: ``grow`` shares every table buffer with its input — so the
        #: first ``apply_delta`` must copy (``donate=False``), which
        #: rebuilds all donate targets fresh and transfers ownership;
        #: every later delta keeps the in-place churn fast path.
        self._graph_donate_safe = False
        # Graph-identity fingerprint caches (computed lazily, only when
        # a store needs them): the edge-content sha survives growth
        # (edges untouched) but not deltas; the full fingerprint caches
        # until any mutation lands.
        self._edges_sha: Optional[str] = None
        self._graph_fp: Optional[str] = None
        self._graph_fp_base: Optional[str] = None

        reg = registry if registry is not None \
            else telemetry.default_registry()
        self._m_submitted = reg.counter(
            "serve_submitted_total",
            "Broadcast submissions accepted by the serving front-end.",
            ("tenant",))
        self._m_rejected = reg.counter(
            "serve_rejected_total",
            "Submissions load-shed by the serving front-end, by reason "
            "(queue_full = lanes busy and the bounded FIFO at depth; "
            "quota = tenant token bucket empty this tick; memory_budget "
            "= the graftmem capacity plan prices the footprint past "
            "hbm_budget_bytes).", ("reason",))
        self._m_completed = reg.counter(
            "serve_completed_total",
            "Tickets whose broadcast reached its coverage target.")
        self._m_cancelled = reg.counter(
            "serve_cancelled_total", "Tickets cancelled by the client.")
        self._m_timeout = reg.counter(
            "serve_timeouts_total",
            "Tickets cut off at max_ticket_rounds before reaching target.")
        self._m_ticks = reg.counter(
            "serve_ticks_total", "Driver admission-loop iterations.")
        self._m_queue = reg.gauge(
            "serve_queue_depth",
            "Submissions waiting for a lane in the bounded FIFO.")
        self._m_active = reg.gauge(
            "serve_active_lanes",
            "Lanes currently running a ticket's broadcast (the host-side "
            "twin of sim_batch_active_lanes, sampled at tick boundaries).")
        self._m_budget = reg.gauge(
            "serve_admit_budget",
            "Current per-tick admission budget (AIMD-paced when "
            "slo_rounds is set).")
        self._m_latency_rounds = reg.histogram(
            "serve_completion_rounds",
            "Submit-to-completion latency in engine rounds (queue wait "
            "included), one observation per completed ticket.",
            buckets=_LATENCY_ROUND_BUCKETS)
        self._m_latency_s = reg.histogram(
            "serve_latency_seconds",
            "Submit-to-completion wall latency per completed ticket.")
        self._m_phase = reg.histogram(
            "serve_tick_phase_seconds",
            "Per-tick wall time of each driver phase (graftsight "
            "tick-phase profiler): retire/admit/dispatch/harvest/"
            "checkpoint.", ("phase",), buckets=_PHASE_SECOND_BUCKETS)
        self._m_phase_wall = reg.gauge(
            "serve_tick_phase_wall_s",
            "Last tick's wall time per driver phase — a gauge so the "
            "history ring samples it next to the engine's per-run "
            "occupancy/ici columns.", ("phase",))
        self._m_healed_ticks = reg.counter(
            "serve_healed_ticks_total",
            "Driver ticks whose engine chunk needed the Healer "
            "(faulted, then recovered within the retry budget).")
        self._m_mutations = reg.counter(
            "serve_mutations_total",
            "Live graph mutations applied by the driver's mutate tick "
            "phase, by kind (delta = GraphDelta edge churn; grow = node "
            "growth, with or without a capacity repad).", ("kind",))
        self._m_capacity = reg.gauge(
            "graph_capacity",
            "Padded node capacity of the served graph (grows in "
            "geometric repad steps under Graph.grow; the static shape "
            "every compiled consumer is keyed on).")
        self._m_capacity.set(float(graph.n_nodes_padded))
        self._m_journal_lag = reg.gauge(
            "serve_journal_lag",
            "Journal records past the last published checkpoint pair "
            "(last appended seqno minus the pair's covered seqno) — the "
            "replay debt a crash right now would pay, sampled at each "
            "publish.")
        # Tick-phase profile state: written by the driver, snapshotted
        # by /dashboard scrape threads — its own small lock, never
        # nested with _cond.
        self._phase_lock = concurrency.lock()
        self._phase_ring: List[dict] = []  # bounded below
        self._phase_totals: Dict[str, float] = {}
        self._phase_max: Dict[str, float] = {}
        self._phase_ticks = 0

        self._store: Optional[CheckpointStore] = None
        self._journal: Optional[Journal] = None
        if journal_fsync not in ("record", "tick", "off"):
            raise ValueError(
                f"journal_fsync must be 'record', 'tick' or 'off', "
                f"got {journal_fsync!r}")
        if journal and store is None:
            raise ValueError(
                "journal=True needs a checkpoint store (the journal "
                "lives in the store directory; pass store=...)")
        if store is not None:
            self._store = store if isinstance(store, CheckpointStore) \
                else CheckpointStore(store, retain=retain, registry=registry)
            if self._store.retain < 2:
                # retain=1 has a trail-losing window: save() of pair N+1
                # prunes entry N BEFORE the new sidecar publishes, so a
                # kill between the two leaves the surviving sidecar
                # pointing at a deleted entry — resume would discard
                # everything. Two entries guarantee the referenced one
                # survives its successor's prune.
                raise ValueError(
                    "graftserve needs a checkpoint store with retain >= 2 "
                    "(retain=1 can prune the entry the current sidecar "
                    "references before the next sidecar lands)")
            # The as-constructed fingerprint, BEFORE any resume-replayed
            # growth: what a later resume of this trail must present.
            self._graph_fp_base = self._graph_fingerprint()
            if not resume:
                # Clear BEFORE the journal constructs: the fresh journal
                # then scans a clean directory instead of recovering a
                # trail the caller just discarded.
                self._clear_trail()
            if journal is None or journal:
                self._journal = Journal(self._store.directory,
                                        fsync=journal_fsync,
                                        registry=registry)
            if resume:
                self._try_resume()
                if self._journal is not None:
                    # The replay suffix: every record the restored pair
                    # does not cover. With no pair at all (a kill before
                    # the first checkpoint) _j_acked is 0 and EVERY
                    # recovered record replays onto the fresh state.
                    covered = self._j_acked
                    self._replay_queue = [
                        r for r in self._journal.records()
                        if int(r["seq"]) > covered]
            if self._journal is not None:
                self._journal.epoch = self._epoch

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "SimService":
        """Spawn the background driver thread (production mode). The
        deterministic alternative is calling :meth:`tick` yourself —
        serve/traffic.py's :func:`~p2pnetwork_tpu.serve.traffic.drive`
        does, which is what makes seeded runs replayable."""
        with self._cond:
            if self._closed:
                raise ServiceClosed("service is closed")
            if self._thread is not None:
                return self
            self._thread = concurrency.thread(  # graftlint: ignore[lock-open-call] -- the seam factory only constructs; start/close must agree on ONE driver
                target=self._driver_loop, name="SimService-driver",
                daemon=True)
            self._thread.start()  # graftlint: ignore[lock-open-call] -- same single-driver atomicity; start() does not block
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop the driver and refuse further submissions (idempotent).
        Queued tickets stay ``queued``; a later service constructed on
        the same store resumes them — which is why a clean close takes
        one FINAL checkpoint after the driver has stopped: submissions
        accepted since the last tick's boundary would otherwise be
        absent from the trail (and their persisted ticket counter
        rolled back, re-issuing their ids to different requests). The
        final checkpoint is skipped when the driver died or cannot be
        joined (the batch may be mid-mutation) and after a
        :class:`Preempted` kill (resume semantics want the PRE-kill
        durable pair)."""
        with self._cond:
            first_close = not self._closed
            self._closed = True
            thread = self._thread
            self._thread = None
            self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        joined = True
        if thread is not None:
            thread.join(timeout=timeout)
            joined = not thread.is_alive()
        if self._watchdog is not None:
            self._watchdog.close()
            self._watchdog = None
        # Re-read the driver's fate AFTER the join: a tick in flight
        # when close() started may still die (or fire an armed
        # preemption) before it observes _closed — a pre-join snapshot
        # would miss that and publish the forbidden post-kill pair.
        with self._cond:
            err = self._driver_error
            dirty = self._dirty
        if not joined:
            warnings.warn(
                "graftserve: close() timed out joining the driver thread "
                "— it may still be mid-tick and could publish one more "
                "checkpoint pair; do not resume a new service on the "
                "same store until it exits", RuntimeWarning, stacklevel=2)
        if (first_close and joined and err is None and dirty
                and self._store is not None):
            try:
                self._checkpoint()
            except Exception as e:  # a failing final save must not mask
                # the close; the trail just ends at the last boundary.
                warnings.warn(
                    f"graftserve: final close checkpoint failed "
                    f"({type(e).__name__}: {e}); the trail ends at the "
                    "last tick boundary", RuntimeWarning, stacklevel=2)
        if first_close and self._journal is not None:
            # After the final pair (so its rotate/compact ran). Any
            # intent the final pair does NOT cover — journaled-but-
            # unapplied mutations, a skipped final checkpoint — stays
            # in surviving segments for the next resume's replay.
            self._journal.close()

    def __enter__(self) -> "SimService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def arm_preemption(self, at_tick: int) -> None:
        """Arm a one-shot deterministic kill: :class:`Preempted` raises
        out of the tick whose completed-tick count reaches ``at_tick``,
        BEFORE the checkpoint due at that boundary — exactly the damage
        a real SIGKILL there inflicts (supervise-plane semantics). A new
        service on the same store resumes from the last durable pair."""
        with self._cond:
            self._preempt_at = int(at_tick)

    # ------------------------------------------------------ live mutations

    def apply_delta(self, delta: "graph_mod.GraphDelta") -> None:
        """Queue an edge-churn :class:`~p2pnetwork_tpu.sim.graph.GraphDelta`
        for the next tick's mutate phase.

        Mutations apply atomically BETWEEN serve ticks (never inside a
        dispatched chunk): the driver drains the queue first thing each
        tick, in submission order, before retire/admit/dispatch — so a
        chunk either entirely precedes or entirely follows a mutation,
        admitted lanes are never dropped, and tickets completed before
        the mutation tick keep byte-identical results (latched lanes are
        never recomputed). Endpoints are validated HERE, against the
        node count the delta will see after any growth already queued
        ahead of it — a bad id raises a typed
        :class:`~p2pnetwork_tpu.sim.graph.EdgeEndpointError` at the
        caller, not an opaque failure inside the driver."""
        reject: Optional[Rejected] = None
        with self._cond:
            if self._closed:
                raise ServiceClosed(self._driver_error or "service is closed")
            if self._durability_lost is not None:
                reject = DurabilityLost(
                    f"durability lost ({self._durability_lost}) — the "
                    "journal cannot acknowledge this delta",
                    detail=self._durability_lost)
            else:
                n_eff = self.graph.n_nodes + sum(
                    p for k, p, _s in self._mutations if k == "grow")
                graph_mod._check_endpoints(  # graftlint: ignore[lock-open-call] -- pure host numpy bounds check; must be atomic with the queue append vs concurrent growers
                    delta.add_senders, delta.add_receivers, n_eff)
                graph_mod._check_endpoints(  # graftlint: ignore[lock-open-call] -- pure host numpy bounds check; must be atomic with the queue append vs concurrent growers
                    delta.remove_senders, delta.remove_receivers, n_eff)
                try:
                    seq = self._journal_append_locked(
                        "delta", **_delta_fields(delta))
                except OSError:
                    reject = DurabilityLost(
                        f"journal append failed "
                        f"({self._durability_lost}) — delta refused",
                        detail=self._durability_lost)
                else:
                    self._mutations.append(("delta", delta, seq))
                    if seq is not None:
                        self._j_pending_mut.append(seq)
                    self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        if reject is not None:
            with self._cond:
                self._counts["rejected"] += 1
                self._dirty = True  # shed counts survive resume too
            self._m_rejected.labels(reject.reason).inc()
            if self._slo is not None:
                self._slo.record("shed", 1.0)
            raise reject

    def _planned_footprint_bytes(self, n_padded: int) -> Optional[int]:
        """Per-chip planned HBM bytes of the serving program at a node
        capacity (graftmem closed form: checked-in coefficients, pure
        host arithmetic). None when no capacity model is available —
        only reachable with the gate disabled, since construction
        refuses the knob without a model."""
        from p2pnetwork_tpu.analysis.ir import capacity as _capacity

        lane_words = -(-self.capacity // 32)
        return _capacity.serving_footprint_bytes(
            int(n_padded), int(self.graph.n_edges_padded), lane_words,
            model=self._cap_model)

    def _planned_capacity_nodes(self, extra_nodes: int = 0) -> int:
        """Padded node capacity once every QUEUED grow (plus
        ``extra_nodes``) lands — the geometric repad schedule
        (graph.growth_capacity) applied to the pending demand. Caller
        holds ``self._cond`` (reads ``_mutations``)."""
        demand = self.graph.n_nodes + int(extra_nodes) + sum(
            p for k, p, _s in self._mutations if k == "grow")  # graftlint: ignore[lock-guard] -- caller holds self._cond (documented contract above)
        current = self.graph.n_nodes_padded
        if demand <= current:
            return current
        return graph_mod.growth_capacity(demand, current)

    def grow(self, n_new_nodes: int) -> None:
        """Queue live overlay growth: ``n_new_nodes`` fresh live nodes
        (ids continuing from the current count) join at the next tick's
        mutate phase via :func:`~p2pnetwork_tpu.sim.graph.grow`.

        When the grown count exceeds the padded capacity the graph
        repads geometrically and the in-flight batch zero-extends with
        it (``MessageBatch.repad``) — zero admitted lanes dropped, the
        latched-completion contract preserved; compiled consumers
        recompile at the new static shape on their next dispatch. Wire
        the new nodes' edges with :meth:`apply_delta` afterwards."""
        n_new_nodes = int(n_new_nodes)
        if n_new_nodes < 0:
            raise ValueError("n_new_nodes must be >= 0")
        reject: Optional[Rejected] = None
        with self._cond:
            if self._closed:
                raise ServiceClosed(self._driver_error or "service is closed")
            if self._durability_lost is not None:
                reject = DurabilityLost(
                    f"durability lost ({self._durability_lost}) — the "
                    "journal cannot acknowledge this growth",
                    detail=self._durability_lost)
            elif self.hbm_budget_bytes is not None:
                planned_cap = self._planned_capacity_nodes(n_new_nodes)
                planned = self._planned_footprint_bytes(planned_cap)
                if planned is not None and planned > self.hbm_budget_bytes:
                    # Refused BEFORE the mutation queues: a growth the
                    # plan prices over budget must never reach the
                    # driver's mutate phase, where the repad would OOM
                    # mid-tick instead of 429-ing here.
                    reject = MemoryBudgetExceeded(
                        f"growth to {planned_cap} padded nodes plans "
                        f"{planned} bytes/chip, over hbm_budget_bytes="
                        f"{int(self.hbm_budget_bytes)} — shard or raise "
                        "the budget",
                        planned_bytes=int(planned),
                        hbm_budget_bytes=int(self.hbm_budget_bytes),
                        planned_capacity=int(planned_cap))
            if reject is None:
                try:
                    seq = self._journal_append_locked("grow",
                                                      n=n_new_nodes)
                except OSError:
                    reject = DurabilityLost(
                        f"journal append failed "
                        f"({self._durability_lost}) — growth refused",
                        detail=self._durability_lost)
            if reject is None:
                self._mutations.append(("grow", n_new_nodes, seq))
                if seq is not None:
                    self._j_pending_mut.append(seq)
                self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        if reject is not None:
            with self._cond:
                self._counts["rejected"] += 1
                self._dirty = True  # shed counts survive resume too
            self._m_rejected.labels(reject.reason).inc()
            if self._slo is not None:
                self._slo.record("shed", 1.0)
            raise reject

    # ---------------------------------------------------------- request API

    def submit(self, source: int, *, target_coverage: float = 0.99,
               tenant: str = "default") -> str:
        """Accept one broadcast request; returns its ticket id.

        Sheds instead of erroring when the service is saturated: every
        lane busy and the FIFO at ``queue_depth`` raises
        :class:`QueueFull`; an empty tenant token bucket raises
        :class:`QuotaExceeded`; a planned footprint past
        ``hbm_budget_bytes`` (pending growth included) raises
        :class:`MemoryBudgetExceeded` — all carry the backpressure
        numbers and count into ``serve_rejected_total{reason}``. A bad
        ``source`` is a caller error (plain ``ValueError``), not a
        shed."""
        source = int(source)
        if not 0 <= source < self.graph.n_nodes_padded:
            raise ValueError(
                f"source {source} outside node range "
                f"[0, {self.graph.n_nodes_padded})")
        target = float(target_coverage)
        if not 0.0 < target <= 1.0:
            raise ValueError(f"target_coverage must be in (0, 1], "
                             f"got {target}")
        tenant = str(tenant)
        reject: Optional[Rejected] = None
        # Wall timestamp taken before the lock, recorded inside it (in
        # the same critical section that publishes the ticket): a
        # second acquisition after publication would race a fast
        # driver completing the ticket first, losing the
        # serve_latency_seconds observation and leaking the entry.
        # It feeds ONLY that histogram — records stay wall-free.
        now = time.perf_counter()
        with self._cond:
            if self._closed:
                raise ServiceClosed(
                    self._driver_error or "service is closed")
            planned = None
            if self.hbm_budget_bytes is not None:
                planned = self._planned_footprint_bytes(
                    self._planned_capacity_nodes())
            if self._durability_lost is not None:
                # Loud degradation (graftdur): an un-journalable submit
                # must never be acknowledged — it would vanish on the
                # next crash while the caller holds a ticket id.
                reject = DurabilityLost(
                    f"durability lost ({self._durability_lost}) — "
                    "shedding until the service is reconstructed on a "
                    "healthy volume", detail=self._durability_lost)
            elif planned is not None and planned > self.hbm_budget_bytes:
                # The service is over-plan (queued growth will repad past
                # the budget): stop taking load before the repad lands.
                reject = MemoryBudgetExceeded(
                    f"planned footprint {planned} bytes/chip over "
                    f"hbm_budget_bytes={int(self.hbm_budget_bytes)} "
                    "(pending growth repads past the plan) — back off",
                    planned_bytes=int(planned),
                    hbm_budget_bytes=int(self.hbm_budget_bytes),
                    planned_capacity=int(self._planned_capacity_nodes()))
            elif tenant in self._quotas and self._buckets.get(tenant, 0.0) < 1.0:
                reject = QuotaExceeded(
                    f"tenant {tenant!r} out of quota this tick "
                    f"(refills at the next driver tick)",
                    tenant=tenant,
                    tokens=self._buckets.get(tenant, 0.0),
                    refill_per_tick=self._quotas[tenant][0])
            elif len(self._queue) >= self.queue_depth:
                # The FIFO is strictly bounded: it only builds when
                # admission (lanes + pacing) runs behind arrivals, so a
                # full queue IS the lane-exhaustion backpressure signal,
                # surfaced with the occupancy numbers a client backs
                # off on.
                reject = QueueFull(
                    f"queue at depth {len(self._queue)}/"
                    f"{self.queue_depth} with "
                    f"{len(self._lane_ticket)}/{self.capacity} lanes "
                    "busy — back off and retry",
                    queue_depth=len(self._queue),
                    queue_limit=self.queue_depth,
                    active_lanes=len(self._lane_ticket),
                    capacity=self.capacity)
            else:
                # Append-before-ack (graftdur): the ticket id is
                # journaled BEFORE the counter advances or the record
                # exists, so acknowledged ⟺ journaled. A failing append
                # leaves NO partial ticket and sheds DurabilityLost; a
                # kill mid-append aborts the submit entirely (the caller
                # never saw an id — nothing was lost).
                tid = f"t{self._next_ticket:08d}"
                try:
                    self._journal_append_locked(
                        "submit", ticket=tid, source=source,
                        target=target, tenant=tenant,
                        round=self._round)
                except OSError:
                    reject = DurabilityLost(
                        f"journal append failed "
                        f"({self._durability_lost}) — submit refused",
                        detail=self._durability_lost)
            if reject is None:
                if tenant in self._quotas:
                    self._buckets[tenant] -= 1.0
                self._next_ticket += 1
                self._tickets[tid] = {
                    "ticket": tid, "tenant": tenant, "source": source,
                    "target": target, "status": "queued",
                    "submitted_tick": self._tick,
                    "submitted_round": self._round,
                    "admitted_tick": None, "admitted_round": None,
                    "lane": None, "rounds": None, "seen_count": None,
                    "coverage": None, "latency_rounds": None,
                }
                self._queue.append(tid)
                self._submit_walls[tid] = now
                self._dirty = True
                self._counts["submitted"] += 1
                depth = len(self._queue)
                self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        if reject is not None:
            with self._cond:
                self._counts["rejected"] += 1
                self._dirty = True  # shed counts survive resume too
                if (self._durability_lost is None
                        and reject.reason != "durability"):
                    # Sheds are admission-plane intents too: journaling
                    # them keeps replay positional (the drive maps each
                    # arrival to exactly one record). Best-effort — a
                    # failure here flips DurabilityLost for the NEXT
                    # admission; this one already sheds.
                    try:
                        self._journal_append_locked(
                            "shed", reason=reject.reason, source=source,
                            tenant=tenant)
                    except OSError:
                        pass
            self._m_rejected.labels(reject.reason).inc()
            if self._slo is not None:
                self._slo.record("shed", 1.0)
            raise reject
        # Bound metric cardinality: only configured tenants (and the
        # default) get their own label child — arbitrary client-supplied
        # tenant strings from the HTTP surface collapse to "other"
        # (ticket records keep the raw tenant either way).
        label = tenant if (tenant == "default" or tenant in self._quotas) \
            else "other"
        self._m_submitted.labels(label).inc()
        self._m_queue.set(float(depth))
        if self._slo is not None:
            self._slo.record("shed", 0.0)
        if spans.current_tracer() is not None:
            spans.emit("ticket_submit", trace=ticket_trace(tid),
                       ticket=tid, source=source, tenant=tenant)
        return tid

    def poll(self, ticket: str) -> Optional[dict]:
        """The ticket's current record (a copy), or ``None`` for an
        unknown/evicted id. Records are fully deterministic — ticks,
        rounds, counts; never wall timestamps."""
        with self._cond:
            rec = self._tickets.get(str(ticket))
            return dict(rec) if rec is not None else None

    def cancel(self, ticket: str) -> bool:
        """Cancel a queued or running ticket; True when this call
        transitioned it. A running lane is recycled at the next tick
        boundary (its partial broadcast is abandoned)."""
        cancelled = False
        with self._cond:
            if self._closed:
                # Symmetric with submit(): after close nothing can reach
                # the durable trail, so a cancellation must not be
                # "accepted" and then silently lost on resume.
                return False
            rec = self._tickets.get(str(ticket))
            if (rec is not None
                    and rec["status"] in ("queued", "running")):
                if self._durability_lost is not None:
                    raise DurabilityLost(
                        f"durability lost ({self._durability_lost}) — "
                        "the journal cannot acknowledge this "
                        "cancellation", detail=self._durability_lost)
                try:
                    # Append-before-ack, like submit: a cancellation the
                    # journal never saw would resurrect the ticket on
                    # replay.
                    self._journal_append_locked("cancel",
                                                ticket=str(ticket))
                except OSError as e:
                    raise DurabilityLost(
                        f"journal append failed "
                        f"({self._durability_lost}) — cancellation "
                        "refused", detail=self._durability_lost) from e
            if rec is not None and rec["status"] == "queued":
                rec["status"] = "cancelled"
                self._queue = [t for t in self._queue if t != rec["ticket"]]
                self._mark_terminal_locked(rec["ticket"])
                cancelled = True
            elif rec is not None and rec["status"] == "running":
                rec["status"] = "cancelled"
                lane = rec["lane"]
                if lane is not None:
                    self._lane_ticket.pop(lane, None)
                    self._cancel_lanes.append(lane)
                # lane is None while the ticket is mid-admission (the
                # driver popped it from the queue but has not assigned
                # its lane yet): _admit_on_device sees the terminal
                # status when it records the mapping and routes the
                # freshly assigned lane to _cancel_lanes itself —
                # appending None here would crash the next tick's
                # retire and kill the driver.
                self._mark_terminal_locked(rec["ticket"])
                cancelled = True
            if cancelled:
                self._counts["cancelled"] += 1
                self._dirty = True
                self._submit_walls.pop(str(ticket), None)
                self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        if cancelled:
            self._m_cancelled.inc()
        return cancelled

    def wait(self, ticket: str, timeout: Optional[float] = None) -> dict:
        """Block until the ticket reaches a terminal state; returns its
        record. The await side of the API — ``/poll`` is the polling
        side. Raises ``KeyError`` for unknown ids, ``TimeoutError`` on
        deadline, :class:`ServiceClosed` if the driver dies first."""
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        snap, _, _ = self._await_ticket(ticket, deadline, timeout,
                                        until_tick_change=False)
        return snap

    def stream(self, ticket: str, timeout: Optional[float] = None):
        """Yield the ticket's record after every driver tick until it
        goes terminal (the last yield) — the streaming view of
        :meth:`wait`. Same error contract as :meth:`wait`."""
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        last_tick = -1
        seen_once = False
        while True:
            snap, last_tick, seen_once = self._await_ticket(
                ticket, deadline, timeout, until_tick_change=True,
                last_tick=last_tick, seen_once=seen_once)
            yield snap
            if snap["status"] in TERMINAL_STATES:
                return

    def _await_ticket(self, ticket: str, deadline: Optional[float],
                      timeout: Optional[float], *,
                      until_tick_change: bool, last_tick: int = -1,
                      seen_once: bool = False):
        """The shared condition-wait core of :meth:`wait` /
        :meth:`stream` (ONE copy of the error contract both promise):
        block until the ticket goes terminal — or, when
        ``until_tick_change``, until the driver tick advances — and
        return ``(snapshot, tick, seen_once)``."""
        with self._cond:
            while True:
                rec = self._tickets.get(str(ticket))
                if rec is None:
                    # A ticket that WAS visible and then vanished was
                    # evicted past done_retention before this waiter
                    # woke — its result is gone, but say so honestly
                    # instead of claiming the id never existed.
                    raise KeyError(
                        f"ticket {ticket!r} evicted past done_retention="
                        f"{self.done_retention} before the waiter "
                        "observed its result — raise done_retention"
                        if seen_once else f"unknown ticket {ticket!r}")
                seen_once = True
                if (rec["status"] in TERMINAL_STATES
                        or (until_tick_change and self._tick != last_tick)):
                    return dict(rec), self._tick, seen_once
                if self._closed:
                    raise ServiceClosed(
                        self._driver_error or "service closed while waiting")
                remaining = 1.0 if deadline is None \
                    else deadline - time.monotonic()  # graftlint: ignore[lock-open-call] -- pure stdlib clock read; the deadline re-check must be atomic with the state re-check
                if remaining <= 0:
                    raise TimeoutError(  # graftlint: ignore[lock-open-call] -- exception construction unwinds the with block; nothing foreign runs under the lock after it
                        f"ticket {ticket} not terminal after {timeout}s")
                self._cond.wait(timeout=min(remaining, 1.0))  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked

    def tickets(self) -> Dict[str, dict]:
        """Copies of every retained ticket record (determinism probes,
        the chaos-soak comparison)."""
        with self._cond:
            return {tid: dict(rec) for tid, rec in self._tickets.items()}

    def busy(self) -> bool:
        """True while anything is queued or running."""
        with self._cond:
            return bool(self._queue or self._lane_ticket)

    @property
    def driver_running(self) -> bool:
        """True while the background driver thread owns :meth:`tick` —
        synchronous drivers (serve/traffic.drive) must refuse to run
        concurrently with it (the batch is driver-confined)."""
        with self._cond:
            return self._thread is not None

    @property
    def tick_index(self) -> int:
        """Completed driver ticks (what traffic replay aligns on)."""
        with self._cond:
            return self._tick

    @property
    def round_index(self) -> int:
        """Cumulative engine rounds executed."""
        with self._cond:
            return self._round

    def stats(self) -> dict:
        """The ``/stats`` document: queue/lane occupancy, admission
        budget, lifetime counts and completion-rounds percentiles (over
        a rolling window of recent completions)."""
        with self._cond:
            lat = list(self._latencies)
            doc = {
                "capacity": self.capacity,
                "graph_nodes": self.graph.n_nodes,
                "graph_capacity": self.graph.n_nodes_padded,
                "mutations_queued": len(self._mutations),
                "queue_depth": len(self._queue),
                "queue_limit": self.queue_depth,
                "active_lanes": len(self._lane_ticket),
                # cancel-pending lanes left the running map but stay
                # admitted on device until the next retire — not free.
                "free_lanes": max(0, self.capacity - len(self._lane_ticket)
                                  - len(self._cancel_lanes)),
                "admit_budget": self._admit_budget,
                "target_active_lanes": self._target_active,
                "tick": self._tick,
                "round": self._round,
                "messages": self._messages,
                "tickets_retained": len(self._tickets),
                "closed": self._closed,
                "quota_tokens": dict(self._buckets),
                # graftdur durability fields: the fencing epoch, why
                # the service is shedding (None while durable), the
                # unreplayed journal suffix, and the seqno a pair
                # published now would cover.
                "epoch": self._epoch,
                "durability_lost": self._durability_lost,
                "replay_pending": len(self._replay_queue),
                "journal_covered": self._j_covered_locked()
                if self._journal is not None else None,
                **self._counts,
            }
        if self._journal is not None:
            doc["journal"] = self._journal.stats()
        if lat:
            doc["completion_rounds_p50"] = float(np.percentile(lat, 50))
            doc["completion_rounds_p99"] = float(np.percentile(lat, 99))
        return doc

    # ------------------------------------------------------------- the tick

    def tick(self) -> dict:
        """One driver iteration: retire recycled lanes, admit from the
        queue under the pacing budget, advance every running lane one
        ``chunk_rounds`` engine chunk, harvest completions, checkpoint.
        Synchronous and deterministic — the background driver just calls
        this in a loop. Returns ``{"admitted", "completed",
        "executed_rounds", "running", "active"}`` for harness
        bookkeeping (``running`` = lanes in flight during this tick's
        engine chunk, ``active`` = still running after harvest).

        Every tick is profiled into the :data:`TICK_PHASES` wall
        breakdown (``serve_tick_phase_seconds{phase}``, the last-tick
        gauges the history ring samples, and the ``/dashboard`` tick
        slice); with a tracer installed the tick additionally emits a
        ``serve_tick`` span with per-phase children plus per-ticket
        correlated lifecycle events under ``tkt-<ticket>`` trace ids
        (:func:`ticket_trace`). Wall times never enter ticket records
        — the profiler does not move the determinism contract."""
        tracer = spans.current_tracer()
        pc = _PhaseClock(tracer)
        # Mutate first: queued graph deltas / growth land atomically
        # BEFORE this tick's chunk, so the dispatch below runs entirely
        # against the post-mutation graph (and a repadded batch) — never
        # mid-chunk, never half-applied.
        pc.enter("mutate")
        with self._cond:
            if self._closed:
                raise ServiceClosed(self._driver_error or "service is closed")
            # Replay fallback (graftdur): recovered journal records due
            # at or before this tick apply now — drives consume the
            # suffix positionally BEFORE calling tick(), so anything
            # still here belongs to an earlier slot (a non-drive
            # resume). Records for later ticks stay queued.
            while (self._replay_queue
                   and int(self._replay_queue[0].get("tick", 0))  # graftlint: ignore[host-sync-in-loop] -- journal records are parsed JSON (host ints), never device values
                   <= self._tick):
                self._replay_apply_locked(self._replay_queue.pop(0))
            # Snapshot-then-clear under the lock: the drained list is a
            # fresh private copy, so iterating it during the (slow,
            # lock-free) apply below never touches shared state.
            muts, self._mutations = list(self._mutations), []
        if muts:
            self._apply_mutations(muts)
        pc.enter("retire")
        if self._watchdog is None and self.deadline_s is not None:
            self._watchdog = Watchdog(
                self.deadline_s, name="serve-driver",
                on_stall=self.on_stall, registry=self._registry).start()
        if self._watchdog is not None:
            self._watchdog.heartbeat()
        with self._cond:
            if self._closed:
                raise ServiceClosed(self._driver_error or "service is closed")
            for tenant, (rate, burst) in self._quotas.items():
                self._buckets[tenant] = min(
                    burst, self._buckets.get(tenant, burst) + rate)
            retire = list(self._cancel_lanes)
            self._cancel_lanes = []
        retire.extend(self._retire_ready)
        self._retire_ready = []
        if retire:
            self._batch = self._protocol.retire(self._batch, sorted(retire))

        # Admission under the pacing budget: free lanes are the
        # non-running ones (every harvested/cancelled lane was just
        # retired above) MINUS any cancel that landed since that retire
        # snapshot — its lane left _lane_ticket but is still admitted
        # on the device until the NEXT tick's retire, so counting it
        # free would over-admit and trip admit()'s LaneExhausted. No
        # device sync needed either way.
        pc.enter("admit")
        admits: List[Tuple[str, int, float]] = []
        with self._cond:
            free = max(0, self.capacity - len(self._lane_ticket)
                       - len(self._cancel_lanes))
            budget = min(
                free, self._admit_budget,
                max(0, self._target_active - len(self._lane_ticket)))
            while self._queue and len(admits) < budget:
                tid = self._queue.pop(0)
                rec = self._tickets[tid]
                rec["status"] = "running"
                rec["admitted_tick"] = self._tick
                rec["admitted_round"] = self._round
                admits.append((tid, rec["source"], rec["target"]))
            round0 = self._round
            tick0 = self._tick
        if admits:
            self._admit_on_device(admits)

        # One compiled chunk for every running lane (skipped when idle).
        pc.enter("dispatch")
        lane_tids: List[Tuple[int, str]] = []
        with self._cond:
            running = len(self._lane_ticket)
            if tracer is not None and running:
                # Snapshot BEFORE the chunk: these are the tickets the
                # dispatch (and any fault it heals through) served.
                lane_tids = sorted(self._lane_ticket.items())
        executed = 0
        out: dict = {}
        if running:
            chunk_key = jax.random.fold_in(self._base_key, round0 + 1)
            if self._healer is not None:
                # Healing mode: undonated dispatch (the retained input
                # IS the rollback state), integrity-checked, retried
                # under the policy. The retry re-runs the same chunk
                # key, so a healed tick's results are bit-identical to
                # an undisturbed one and no admitted lane is lost.
                def _dispatch(b):
                    return engine.run_batch_until_coverage(
                        self.graph, self._protocol, b, chunk_key,
                        max_rounds=self.chunk_rounds, donate=False)

                self._batch, out = self._healer.run_chunk(
                    _dispatch, self._batch, chunk_index=tick0)
            else:
                self._batch, out = engine.run_batch_until_coverage(
                    self.graph, self._protocol, self._batch, chunk_key,
                    max_rounds=self.chunk_rounds, donate=True)
            executed = int(out["rounds"])
        heal_report = self._healer.last_report \
            if (self._healer is not None and running) else None
        faulted = bool(heal_report and heal_report["events"])
        if faulted and heal_report["healed"]:
            self._m_healed_ticks.inc()
        if tracer is not None:
            self._emit_ticket_chunk_events(lane_tids, tick0, executed,
                                           heal_report)
        if self._tick_fault is not None:
            # Crash seam (chaos/crashstorm.py): mid-tick, after the
            # dispatch, before any of its results reach the ticket
            # table — the window where a kill costs the most state.
            self._tick_fault(tick0)
        pc.enter("harvest")
        completed = self._harvest(out, executed)
        if self._journal is not None:
            # The per-tick durability barrier (fsync="tick" policy):
            # everything acknowledged this tick reaches the platter
            # before the tick ends. A failing barrier is a durability
            # loss like a failing append — flip and shed, loudly, but
            # keep the driver alive (completed work is still real).
            try:
                self._journal.tick_barrier()
            except OSError as e:
                with self._cond:
                    if self._durability_lost is None:
                        self._durability_lost = (
                            f"journal fsync failed: "
                            f"{type(e).__name__}: {e}")
        if self._slo is not None:
            # One heal observation per DISPATCHING tick (idle ticks are
            # no evidence either way), then the per-tick evaluation.
            # Only deterministic, admission_signal objectives may steer
            # the budget; a firing one is a multiplicative decrease,
            # recovery rides the existing AIMD additive increase.
            if running:
                self._slo.record("heal", 1.0 if faulted else 0.0)
            with self._cond:
                dur_lost = self._durability_lost is not None
            # One durability observation per tick (the graftdur SLO
            # stream — dropped unless the engine declares the
            # objective; see telemetry.slo.serve_objectives).
            self._slo.record("durability", 1.0 if dur_lost else 0.0)
            self._slo.evaluate(tick0)
            if self._slo.firing(admission_only=True):
                with self._cond:
                    self._admit_budget = max(1, self._admit_budget // 2)
                    budget_now = self._admit_budget
                self._m_budget.set(float(budget_now))
        if self._watchdog is not None:
            self._watchdog.heartbeat()
        pc.enter("checkpoint")

        # Checkpoint AFTER the preemption gate: an armed kill fires
        # before the checkpoint due at this boundary, like a real
        # SIGKILL (supervise-plane semantics).
        with self._cond:
            fire_preempt = (self._preempt_at is not None
                            and self._tick >= self._preempt_at)
            if fire_preempt:
                self._preempt_at = None
            if admits or retire or completed or executed:
                self._dirty = True
            dirty = self._dirty
            tick_now = self._tick
            active = len(self._lane_ticket)
            qdepth = len(self._queue)
        self._m_ticks.inc()
        self._m_active.set(float(active))
        self._m_queue.set(float(qdepth))
        if fire_preempt:
            # The kill closes the service like the SIGKILL it simulates:
            # further ticks/submits refuse, and close() must NOT take a
            # final checkpoint (resume wants the PRE-kill durable pair).
            with self._cond:
                self._closed = True
                self._driver_error = f"preempted at tick {tick_now}"
                self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
            raise Preempted(tick_now)
        if (self._store is not None and dirty
                and tick_now % self.checkpoint_every_ticks == 0):
            self._checkpoint()
        self._record_phases(pc.done(tick0), tick0)
        return {"admitted": len(admits), "completed": completed,
                "executed_rounds": executed, "running": running,
                "active": active}

    def _emit_ticket_chunk_events(self, lane_tids: List[Tuple[int, str]],
                                  tick0: int, executed: int,
                                  heal_report: Optional[dict]) -> None:
        """Per-ticket correlated trace events for one dispatched chunk
        (tracer-on only). Every riding ticket gets a ``ticket_chunk``
        point under its ``tkt-<id>`` trace; when the Healer's attempt
        report says the chunk faulted, each ticket also gets the
        fault→integrity-fail→heal-retry(→heal-recovered) chain — the
        chunk is shared, so a fault on it IS an event in every riding
        ticket's lifecycle."""
        events = heal_report["events"] if heal_report else []
        for lane, tid in lane_tids:
            tr = ticket_trace(tid)
            spans.emit("ticket_chunk", trace=tr, ticket=tid, lane=lane,
                       tick=tick0, rounds=executed, faulted=bool(events))
            for ev in events:
                spans.emit("ticket_fault", trace=tr, ticket=tid,
                           kind=ev["failure"], chunk=heal_report["chunk"],
                           attempt=ev["attempt"])
                if "integrity_kind" in ev:
                    spans.emit("ticket_integrity_fail", trace=tr,
                               ticket=tid, kind=ev["integrity_kind"],
                               leaf=ev.get("leaf", ""),
                               chunk=heal_report["chunk"])
                spans.emit("ticket_heal_retry", trace=tr, ticket=tid,
                           attempt=ev["attempt"], action=ev["action"],
                           degraded=ev["degraded"])
            if events and heal_report["healed"]:
                spans.emit("ticket_heal_recovered", trace=tr, ticket=tid,
                           attempts=heal_report["attempts"],
                           fallback=heal_report["fallback"])

    def _record_phases(self, phases: Dict[str, float], tick: int) -> None:
        """Fold one tick's phase walls into the profiler state: the
        per-phase histogram + last-tick gauges (what the history ring
        joins with the flight recorder's per-round columns) and the
        bounded recent-ticks ring behind :meth:`tick_phases`."""
        row = {"tick": tick}
        for ph in TICK_PHASES:
            s = phases.get(ph, 0.0)
            row[ph] = s
            self._m_phase.labels(ph).observe(s)
            self._m_phase_wall.labels(ph).set(s)
        with self._phase_lock:
            self._phase_ticks += 1
            self._phase_ring.append(row)
            if len(self._phase_ring) > 128:
                del self._phase_ring[:-128]
            for ph in TICK_PHASES:
                s = row[ph]
                self._phase_totals[ph] = self._phase_totals.get(ph, 0.0) + s
                if s > self._phase_max.get(ph, 0.0):
                    self._phase_max[ph] = s

    def tick_phases(self) -> dict:
        """The tick-phase profile (graftsight): ``{"ticks", "per_phase":
        {phase: {"total_s", "mean_s", "last_s", "max_s"}}, "recent":
        [last 32 per-tick rows]}``. Thread-safe — what ``/dashboard``
        and the bench ``serving.tick_phases`` slice read."""
        with self._phase_lock:
            ticks = self._phase_ticks
            totals = dict(self._phase_totals)
            maxes = dict(self._phase_max)
            recent = list(self._phase_ring[-32:])
        per_phase = {}
        for ph in TICK_PHASES:
            tot = totals.get(ph, 0.0)
            per_phase[ph] = {
                "total_s": tot,
                "mean_s": tot / ticks if ticks else 0.0,
                "last_s": recent[-1][ph] if recent else 0.0,
                "max_s": maxes.get(ph, 0.0),
            }
        return {"ticks": ticks, "per_phase": per_phase, "recent": recent}

    def dashboard_slice(self) -> dict:
        """What ``/dashboard`` embeds for this service (duck-typed by
        telemetry/httpd.py): the ``/stats`` document plus the
        tick-phase profile."""
        return {"stats": self.stats(), "tick_phases": self.tick_phases()}

    def _admit_on_device(self, admits: List[Tuple[str, int, float]]) -> None:
        """Seed the popped submissions into open lanes, grouped by
        coverage target (``admit`` takes one target per call), and
        record the lane→ticket mapping. Group order is first-appearance,
        so lane assignment is deterministic."""
        groups: Dict[float, List[Tuple[str, int]]] = {}
        for tid, source, target in admits:
            groups.setdefault(target, []).append((tid, source))
        assigned: List[Tuple[int, str]] = []
        for target, entries in groups.items():
            sources = [source for _, source in entries]
            # messagebatch.LaneExhausted is unreachable by
            # construction here (the budget is capped at the free-lane
            # count, cancel-pending lanes excluded); if the invariant
            # ever breaks it propagates loudly rather than silently
            # dropping tickets.
            self._batch, lanes = self._protocol.admit(
                self.graph, self._batch, sources, coverage_target=target)
            assigned.extend(zip(lanes.tolist(), [tid for tid, _ in entries]))
        # Lanes whose SEED already meets the target start done at
        # admission (tiny coverage targets, near-single-node graphs).
        # The engine excludes pre-run-done lanes from
        # ``newly_completed_lanes``, so the chunk harvest would never
        # see them — complete their tickets HERE, or they would pin
        # "running" forever while their lanes leak.
        done_list = np.asarray(self._batch.done).tolist()
        seen_list = np.asarray(self._batch.seen_count).tolist()
        instant = [lane for lane, _ in assigned if done_list[lane]]
        hashes = self._hash_lanes(instant) \
            if (self._record_seen_hash and instant) else {}
        completions: List[Tuple[str, dict]] = []
        with self._cond:
            for lane, tid in assigned:
                rec = self._tickets.get(tid)
                if rec is None:
                    # Cancelled AND evicted past done_retention inside
                    # the unlocked admission gap: nothing left to
                    # record — just recycle the lane.
                    self._cancel_lanes.append(lane)
                    continue
                rec["lane"] = lane
                if rec["status"] in TERMINAL_STATES:
                    # Cancelled while mid-admission (status flipped
                    # between the queue pop and this lock): never runs —
                    # recycle the lane instead of mapping it, or the
                    # harvest would flip a terminal ticket back to done.
                    self._cancel_lanes.append(lane)
                elif done_list[lane]:
                    rec["status"] = "done"
                    rec["rounds"] = 0
                    rec["seen_count"] = seen_list[lane]
                    rec["coverage"] = seen_list[lane] / max(self._n_live, 1)
                    rec["latency_rounds"] = (rec["admitted_round"]
                                             - rec["submitted_round"])
                    if lane in hashes:
                        rec["seen_sha256"] = hashes[lane]
                    self._mark_terminal_locked(tid)
                    self._counts["completed"] += 1
                    self._latencies.append(rec["latency_rounds"])
                    self._cancel_lanes.append(lane)  # recycle next tick
                    completions.append((tid, dict(rec)))
                else:
                    self._lane_ticket[lane] = tid
            walls = [(tid, self._submit_walls.pop(tid, None))
                     for tid, _ in completions]
            if completions:
                self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        if spans.current_tracer() is not None:
            for lane, tid in assigned:
                spans.emit("ticket_admit", trace=ticket_trace(tid),
                           ticket=tid, lane=lane)
        self._report_completions(completions, walls)

    def _report_completions(self, completions: List[Tuple[str, dict]],
                            walls: List[Tuple[str, Optional[float]]]) -> None:
        """Post-lock completion reporting shared by the chunk harvest
        and the instant-done admission path: the completed counter, both
        latency histograms, the ``ticket_done`` trace event."""
        now = time.perf_counter()
        tracer = spans.current_tracer()
        for (tid, rec), (_, t_sub) in zip(completions, walls):
            self._m_completed.inc()
            self._m_latency_rounds.observe(rec["latency_rounds"])
            if self._slo is not None:
                # latency_rounds is a plain int by the time a record is
                # built; record() coerces to float itself.
                self._slo.record("completion_rounds",
                                 rec["latency_rounds"])
            if t_sub is not None:
                self._m_latency_s.observe(now - t_sub)
                if self._slo is not None:
                    self._slo.record("completion_wall_s", now - t_sub)
            if tracer is not None:
                spans.emit("ticket_done", trace=ticket_trace(tid),
                           ticket=tid, rounds=rec["rounds"],
                           latency_rounds=rec["latency_rounds"])

    def _harvest(self, out: dict, executed: int) -> int:
        """Fold one chunk's results back into the ticket table: newly
        completed lanes become ``done`` records (with their latency),
        stragglers past ``max_ticket_rounds`` become ``timeout``; both
        kinds queue for recycling at the next tick's retire."""
        newly = out.get("newly_completed_lanes")
        newly = newly.tolist() if newly is not None else []
        rounds_list = out["lane_rounds"].tolist() if out else []
        seen_hash: Dict[int, str] = {}
        seen_list: List[int] = []
        if out:
            seen_np = np.asarray(self._batch.seen_count)
            seen_list = seen_np.tolist()
            if self._record_seen_hash and newly:
                seen_hash = self._hash_lanes(newly)
        completions: List[Tuple[str, dict]] = []
        recycled: List[int] = []  # folded into the driver-confined
        # _retire_ready AFTER the lock (it is not lock-guarded state)
        with self._cond:
            self._round += executed
            self._messages += int(out["messages"]) if out else 0
            for lane in newly:
                tid = self._lane_ticket.pop(lane, None)
                recycled.append(lane)
                if tid is None:
                    continue  # cancelled mid-chunk; lane already recycled
                rec = self._tickets[tid]
                rec["status"] = "done"
                rec["rounds"] = rounds_list[lane]
                rec["seen_count"] = seen_list[lane]
                rec["coverage"] = seen_list[lane] / max(self._n_live, 1)
                rec["latency_rounds"] = (
                    (rec["admitted_round"] - rec["submitted_round"])
                    + rounds_list[lane])
                if lane in seen_hash:
                    rec["seen_sha256"] = seen_hash[lane]
                self._mark_terminal_locked(tid)
                self._counts["completed"] += 1
                self._latencies.append(rec["latency_rounds"])
                completions.append((tid, dict(rec)))
            if len(self._latencies) > 4096:
                del self._latencies[:-2048]
            # Stragglers past the per-ticket round bound: cut off.
            timed_out: List[Tuple[int, str]] = []
            if rounds_list:
                for lane, tid in list(self._lane_ticket.items()):
                    if rounds_list[lane] >= self.max_ticket_rounds:
                        timed_out.append((lane, tid))
            for lane, tid in timed_out:
                self._lane_ticket.pop(lane, None)
                recycled.append(lane)
                rec = self._tickets[tid]
                rec["status"] = "timeout"
                rec["rounds"] = rounds_list[lane]
                rec["seen_count"] = seen_list[lane]
                rec["coverage"] = seen_list[lane] / max(self._n_live, 1)
                self._mark_terminal_locked(tid)
                self._submit_walls.pop(tid, None)  # never completes
                self._counts["timeout"] += 1
            # AIMD pacing off the chunk's observed completion
            # percentiles: over-SLO p99 halves the budget, a healthy
            # COMPLETING chunk claws back additively. A chunk that
            # completed nothing carries no p99 — if its oldest running
            # lane is already past the SLO that silence IS the overload
            # signal (halve); otherwise it is no evidence either way
            # (hold, never grow — a fully stalled system must not earn
            # additive increase from rounds that finished nothing).
            if self.slo_rounds is not None and out:
                p99 = out.get("completion_rounds_p99")
                oldest = max((rounds_list[lane]
                              for lane in self._lane_ticket), default=0)
                if ((p99 is not None and p99 > self.slo_rounds)
                        or (p99 is None and oldest > self.slo_rounds)):
                    self._admit_budget = max(1, self._admit_budget // 2)
                elif p99 is not None:
                    self._admit_budget = min(
                        self._target_active,
                        self._admit_budget + max(1, self.capacity // 16))
            self._tick += 1
            walls = [(tid, self._submit_walls.pop(tid, None))
                     for tid, _ in completions]
            budget_now = self._admit_budget
            self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
        self._retire_ready.extend(recycled)
        self._report_completions(completions, walls)
        tracer = spans.current_tracer()
        for lane, tid in timed_out:
            self._m_timeout.inc()
            if tracer is not None:
                spans.emit("ticket_timeout", trace=ticket_trace(tid),
                           ticket=tid, lane=lane)
        self._m_budget.set(float(budget_now))
        return len(completions)

    def _hash_lanes(self, lanes: List[int]) -> Dict[int, str]:
        """sha256 of each lane's packed seen bits — one host pull of the
        u32 words, then pure-numpy per-lane extraction."""
        import hashlib

        words = np.asarray(self._batch.seen)  # u32[W, N_pad], one pull
        out = {}
        for lane in lanes:
            w, b = divmod(lane, 32)
            bits = ((words[w] >> np.uint32(b)) & np.uint32(1)).astype(np.uint8)
            out[lane] = hashlib.sha256(np.packbits(bits).tobytes()).hexdigest()
        return out

    def _mark_terminal_locked(self, tid: str) -> None:
        """Bound the terminal-record table (caller holds the lock):
        oldest terminal tickets past ``done_retention`` are evicted (a
        later poll returns None, documented)."""
        self._done_order.append(tid)
        while len(self._done_order) > self.done_retention:
            old = self._done_order.pop(0)
            self._tickets.pop(old, None)
            self._submit_walls.pop(old, None)

    # ------------------------------------------- graftdur durability plane

    def _journal_append_locked(self, kind: str, **fields) -> Optional[int]:
        """Append one admission-plane intent record (caller holds
        ``_cond``); returns its seqno, or ``None`` with no journal
        configured. Any failure flips the service into the sticky
        :class:`DurabilityLost` shedding mode BEFORE propagating — the
        intent was never acknowledged, and nothing after a possibly-torn
        tail may be."""
        if self._journal is None:
            return None
        try:
            seq = self._journal.append(kind, tick=self._tick, **fields)  # graftlint: ignore[lock-open-call] -- the append IS the acknowledgement: it must be atomic with the state change it acknowledges (one unbuffered write; fsync only under the per-record policy)
        except BaseException as e:
            if self._durability_lost is None:
                self._durability_lost = (
                    f"journal append failed: {type(e).__name__}: {e}")
            raise
        self._j_acked = seq
        return seq

    def _j_covered_locked(self) -> int:
        """The seqno a pair published NOW covers (caller holds
        ``_cond``): everything acknowledged, MINUS journaled intents the
        pair does not yet reflect — queued-but-unapplied mutations and
        the unconsumed replay suffix. Compaction keys on this, so those
        intents survive in the journal until something applies them."""
        covered = self._j_acked
        if self._j_pending_mut:
            covered = min(covered, self._j_pending_mut[0] - 1)
        if self._replay_queue:
            covered = min(covered,
                          int(self._replay_queue[0]["seq"]) - 1)
        return covered

    def replay_pending(self) -> int:
        """Journal records recovered at resume and not yet replayed."""
        with self._cond:
            return len(self._replay_queue)

    def replay_peek(self) -> Optional[dict]:
        """The next recovered record awaiting replay (a copy), or
        ``None``. Drives use the ``kind``/``tick`` fields to consume
        positionally — each record at its original arrival slot."""
        with self._cond:
            return dict(self._replay_queue[0]) \
                if self._replay_queue else None

    def replay_next(self) -> Optional[dict]:
        """Replay ONE recovered record onto the service state and
        return it (``None`` when the suffix is exhausted). A replayed
        submit re-issues the SAME ticket id the crashed life
        acknowledged (verified against the persisted counter — a
        divergence is a corrupted-trail error, raised loudly); grows
        and deltas re-queue for the next tick's mutate phase; sheds and
        cancels re-apply their counts/transitions. Process metrics
        count live operations only — replay touches none."""
        with self._cond:
            if not self._replay_queue:
                return None
            rec = self._replay_queue.pop(0)
            self._replay_apply_locked(rec)
            return dict(rec)

    def _replay_apply_locked(self, rec: dict) -> None:
        seq = int(rec["seq"])
        kind = rec.get("kind")
        if kind == "submit":
            tid = str(rec["ticket"])
            want = f"t{self._next_ticket:08d}"
            if tid != want:
                raise RuntimeError(
                    f"journal replay diverged: record {seq} "
                    f"acknowledges ticket {tid!r} but this service "
                    f"would issue {want!r} — the checkpoint pair and "
                    "journal disagree (mixed trails?); refusing to "
                    "re-issue an acknowledged id to different work")
            tenant = str(rec.get("tenant", "default"))
            if tenant in self._quotas:
                self._buckets[tenant] = \
                    self._buckets.get(tenant, 0.0) - 1.0
            self._next_ticket += 1
            self._tickets[tid] = {
                "ticket": tid, "tenant": tenant,
                "source": int(rec.get("source", 0)),
                "target": float(rec.get("target", 0.99)),
                "status": "queued",
                "submitted_tick": int(rec.get("tick", self._tick)),
                "submitted_round": int(rec.get("round", self._round)),
                "admitted_tick": None, "admitted_round": None,
                "lane": None, "rounds": None, "seen_count": None,
                "coverage": None, "latency_rounds": None,
            }
            self._queue.append(tid)
            # No _submit_walls entry: wall latency is a live-process
            # observation; completion handlers tolerate the None.
            self._counts["submitted"] += 1
            self._dirty = True
        elif kind == "shed":
            self._counts["rejected"] += 1
            self._dirty = True
        elif kind == "cancel":
            tid = str(rec.get("ticket"))
            r = self._tickets.get(tid)
            if r is not None and r["status"] == "queued":
                r["status"] = "cancelled"
                self._queue = [t for t in self._queue if t != tid]
                self._mark_terminal_locked(tid)
                self._counts["cancelled"] += 1
                self._dirty = True
            elif r is not None and r["status"] == "running":
                r["status"] = "cancelled"
                lane = r["lane"]
                if lane is not None:
                    self._lane_ticket.pop(lane, None)
                    self._cancel_lanes.append(lane)
                self._mark_terminal_locked(tid)
                self._counts["cancelled"] += 1
                self._dirty = True
        elif kind == "grow":
            self._mutations.append(("grow", int(rec.get("n", 0)), seq))
            self._j_pending_mut.append(seq)
        elif kind == "delta":
            self._mutations.append(
                ("delta", _delta_from_fields(rec), seq))
            self._j_pending_mut.append(seq)
        # Unknown kinds skip silently (forward compatibility) but still
        # advance the acknowledged cover below — they WERE acknowledged.
        if seq > self._j_acked:
            self._j_acked = seq
        self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked

    # ------------------------------------------------------ mutation plane

    def _apply_mutations(
            self, muts: List[Tuple[str, Any, Optional[int]]]) -> None:
        """Drain one tick's queued mutations onto the served graph
        (driver-confined — the graph and batch are the driver's).

        Deltas ride ``apply_delta(donate=...)`` — the first delta
        copies (the constructor graph is caller-owned; see
        ``_graph_donate_safe``), after which every delta takes the
        churn-storm fast path (touched neighbor rows scatter in
        place); growth rides
        ``graph.grow`` with its geometric repad schedule. When the
        padded capacity changes, the in-flight batch zero-extends via
        ``repad`` — zero admitted lanes dropped — and the healer's
        integrity template rebuilds at the new shapes. A failing
        mutation propagates and kills the driver loudly: mutations are
        operator actions, and a half-applied queue must not be
        silently skipped."""
        g = self.graph
        old_pad = g.n_nodes_padded
        for kind, payload, _seq in muts:
            if kind == "grow":
                g = graph_mod.grow(g, payload)
                self._growth_history.append({
                    "tick": self._tick, "n_new": int(payload),  # graftlint: ignore[host-sync-in-loop,lock-guard] -- grow amounts are Python ints; _tick is driver-written and this runs on the driver
                    "n_nodes": int(g.n_nodes),  # graftlint: ignore[host-sync-in-loop] -- static graph field (host int by construction)
                    "n_pad": int(g.n_nodes_padded)})  # graftlint: ignore[host-sync-in-loop] -- static padded capacity (host int by construction)
            else:
                g = graph_mod.apply_delta(
                    g, payload, donate=self._graph_donate_safe)
                self._graph_donate_safe = True
                self._edges_sha = None   # edge content changed
            self._m_mutations.labels(kind).inc()
            if spans.current_tracer() is not None:
                spans.emit("serve_mutation", kind=kind, tick=self._tick,  # graftlint: ignore[lock-guard] -- _tick is driver-written and _apply_mutations runs on the driver
                           n_nodes=int(g.n_nodes),  # graftlint: ignore[host-sync-in-loop] -- static graph field (host int by construction)
                           n_pad=int(g.n_nodes_padded))  # graftlint: ignore[host-sync-in-loop] -- static padded capacity (host int by construction)
        new_pad = g.n_nodes_padded
        self.graph = g
        self._graph_fp = None            # identity changed either way
        if new_pad != old_pad:
            # Capacity repad: the batch's per-node axes zero-extend (no
            # admitted lane touched; latched completions stay latched)
            # and the next dispatch recompiles at the grown shape.
            self._batch = self._protocol.repad(self._batch, new_pad)
            if self._healer is not None:
                self._healer.template = jax.tree_util.tree_map(
                    lambda x: np.zeros(x.shape, x.dtype), self._batch)
        n_live = int(np.sum(np.asarray(g.node_mask)))
        applied = {seq for _, _, seq in muts if seq is not None}
        with self._cond:
            self._n_live = n_live
            self._counts["mutations"] += len(muts)
            self._dirty = True
            if applied:
                # These journaled intents are now IN the service state:
                # the next published pair reflects them, so the cover
                # may advance past their records (a failing mutation
                # propagated above instead — its seq stays pending and
                # the journal keeps the record for the next resume).
                self._j_pending_mut = [
                    s for s in self._j_pending_mut if s not in applied]
        self._m_capacity.set(float(new_pad))

    def _graph_fingerprint(self) -> str:
        """The served graph's identity for the sidecar: the
        sim/layoutcache.py source fingerprint folded with this graph's
        node/edge counts, padded capacity, and edge-content sha. Cached
        until a mutation invalidates it (growth keeps the edge sha —
        edges are untouched — deltas recompute it)."""
        if self._graph_fp is not None:
            return self._graph_fp
        import hashlib

        from p2pnetwork_tpu.sim import layoutcache

        g = self.graph
        if self._edges_sha is None:
            arrs = jax.device_get({"senders": g.senders,
                                   "receivers": g.receivers,
                                   "edge_mask": g.edge_mask})
            h = hashlib.sha256()
            for name in ("senders", "receivers", "edge_mask"):
                h.update(np.ascontiguousarray(arrs[name]).tobytes())
            self._edges_sha = h.hexdigest()[:16]
        self._graph_fp = layoutcache.fingerprint(params={"serve_graph": {
            "n_nodes": int(g.n_nodes), "n_edges": int(g.n_edges),
            "n_pad": int(g.n_nodes_padded), "edges_sha": self._edges_sha,
        }})
        return self._graph_fp

    # ------------------------------------------------------------- driver

    def _driver_loop(self) -> None:
        """Background production driver: tick whenever there is work (or
        on the idle cadence, which keeps tick-based quota refill
        advancing). Any escape — Preempted included — closes the service
        with the error recorded for submitters/waiters."""
        while True:
            with self._cond:
                if self._closed:
                    return
                if not (self._queue or self._lane_ticket
                        or self._cancel_lanes or self._mutations):
                    self._cond.wait(timeout=self.idle_wait_s)  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
                if self._closed:
                    return
            try:
                self.tick()
            except ServiceClosed:
                return  # close() landed between the wait and the tick
            except BaseException as e:
                with self._cond:
                    self._closed = True
                    if self._driver_error is None:
                        # tick() may have recorded a deliberate cause
                        # already (a fired preemption) — keep it, so
                        # both driver modes report the event the same.
                        self._driver_error = f"driver died: " \
                            f"{type(e).__name__}: {e}"
                    self._cond.notify_all()  # graftlint: ignore[lock-open-call] -- Condition.notify_all/wait REQUIRE holding the condition's own lock (stdlib contract); wait releases it while blocked
                if isinstance(e, Preempted):
                    return  # deterministic kill: resume via a new service
                raise

    # -------------------------------------------------------- checkpointing

    def _snapshot_locked(self) -> dict:
        # The pair being built covers everything recorded so far; any
        # mutation after this point re-dirties and re-checkpoints.
        self._dirty = False
        return {
            "version": 1,
            "seed": self.seed,
            "round": self._round,
            "tick": self._tick,
            "next_ticket": self._next_ticket,
            "messages": self._messages,
            "queue": list(self._queue),
            "lanes": {str(k): v for k, v in self._lane_ticket.items()},
            "buckets": dict(self._buckets),
            "admit_budget": self._admit_budget,
            "counts": dict(self._counts),
            "done_order": list(self._done_order),
            "latencies": list(self._latencies),
            "tickets": {tid: dict(rec)
                        for tid, rec in self._tickets.items()},
        }

    def _checkpoint(self) -> str:
        """Durably publish the (batch, ticket-table) pair: the batch
        lands as a content-hashed store entry, then the sidecar is
        rename-published REFERENCING that exact entry — a kill between
        the two leaves the previous consistent pair (the sidecar is the
        resume authority, pointing at a never-rewritten entry within the
        retention window)."""
        # Fencing first (graftdur failover): a zombie primary must fail
        # BEFORE its store entry lands, not after — the promoted epoch
        # owns the trail outright.
        self._check_fence()
        # Graph identity (computed outside the lock — it may pull edge
        # arrays to host): the fingerprint gate resume checks, plus the
        # growth steps that sanction a base-fingerprint resume.
        fp = self._graph_fingerprint()
        with self._cond:
            snap = self._snapshot_locked()
            covered = self._j_covered_locked() \
                if self._journal is not None else None
            ours = self._epoch
        snap["graph_fingerprint"] = fp
        snap["graph_fingerprint_base"] = self._graph_fp_base
        snap["growth"] = [dict(s) for s in self._growth_history]
        snap["epoch"] = ours
        if covered is not None:
            # The journal seqno this pair supersedes: resume replays
            # exactly the records past it.
            snap["journal_seqno"] = covered
        try:
            path = self._store.save(self._batch, self._base_key,
                                    snap["round"], snap["messages"])
            snap["checkpoint_file"] = os.path.basename(path)
            if self._publish_fault is not None:
                # Crash seam (chaos/crashstorm.py): between the store
                # entry and the sidecar rename — the classic torn-pair
                # window the previous consistent pair must survive.
                self._publish_fault(snap["tick"])
            atomic_write_json(
                os.path.join(self._store.directory, _SIDECAR), snap,
                suffix=".side.tmp")
        except BaseException:
            # The pair did NOT publish: put the dirty bit back, or a
            # later clean close() would skip its final checkpoint and
            # silently lose everything since the last successful pair.
            with self._cond:
                self._dirty = True
            raise
        if self._journal is not None:
            # The published pair supersedes the journal prefix up to
            # `covered`: rotate the open segment out and drop every
            # closed segment the pair covers. Best-effort — replay
            # filters on journal_seqno anyway, so a failed unlink only
            # costs disk, never correctness.
            try:
                self._journal.rotate()
                self._journal.compact(covered)
            except OSError:
                pass
            self._m_journal_lag.set(
                float(self._journal.last_seq - covered))
        if spans.current_tracer() is not None:
            spans.emit("serve_checkpoint", tick=snap["tick"],
                       round=snap["round"])
        return path

    def checkpoint(self) -> str:
        """Force one durable (batch, sidecar) pair NOW, outside the
        driver's boundary cadence; returns the store entry path. What
        :meth:`~p2pnetwork_tpu.serve.standby.Standby.promote` calls to
        publish its fencing token immediately. Raises
        :class:`FencedEpoch` if a newer epoch owns the trail, and
        ``ValueError`` without a store."""
        if self._store is None:
            raise ValueError("checkpoint() needs a store (pass store=...)")
        return self._checkpoint()

    def _check_fence(self) -> None:
        """Refuse to publish over a trail a newer epoch owns: read the
        current sidecar's fencing token; above ours means a standby
        promoted while we were presumed dead — we are the zombie."""
        if self._store is None:
            return
        with self._cond:
            ours = self._epoch
        side = os.path.join(self._store.directory, _SIDECAR)
        try:
            with open(side, "r", encoding="utf-8") as f:
                current = int(json.load(f).get("epoch", 0))
        except (OSError, ValueError, TypeError):
            return  # no/unreadable sidecar: nothing fences us
        if current > ours:
            raise FencedEpoch(
                f"checkpoint refused: sidecar fencing token (epoch "
                f"{current}) is newer than ours ({ours}) — a "
                "standby promoted over this trail; this service is a "
                "demoted zombie and must not publish",
                ours=ours, current=current)

    def _clear_trail(self) -> None:
        self._store.clear()
        side = os.path.join(self._store.directory, _SIDECAR)
        try:
            os.unlink(side)
        except OSError:
            pass
        # The journal is part of the trail: a discarded pair must not
        # leave a suffix that would replay onto unrelated fresh state.
        if self._journal is not None:
            self._journal.reset()
        else:
            _clear_journal(self._store.directory)
        # Construction-time path, but these are lock-guarded everywhere
        # else — keep the discipline uniform.
        with self._cond:
            self._replay_queue = []
            self._j_acked = 0
            self._j_pending_mut = []

    def _template(self):
        shapes = jax.eval_shape(
            lambda g: self._protocol.empty(g, self.capacity), self.graph)
        return jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype), shapes)

    def _try_resume(self) -> bool:
        """Restore the newest consistent (checkpoint, sidecar) pair; a
        missing or unloadable pair is a fresh start (stale trails
        cleared, runner semantics)."""
        side_path = os.path.join(self._store.directory, _SIDECAR)
        try:
            with open(side_path, "r", encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError):
            if self._store.entries():
                self._clear_trail()
            return False
        entry = snap.get("checkpoint_file")
        path = os.path.join(self._store.directory, str(entry))
        # Graph-identity gate (trail-preserving): the sidecar's
        # fingerprint must explain the constructed graph — either it IS
        # the trail's graph, or the trail's recorded growth steps grow
        # the construction into it (the sanctioned exception, replayed
        # here so the batch template below already has the grown
        # shapes). Anything else is a wrong-overlay resume: refuse with
        # the trail intact. Legacy sidecars without a fingerprint skip
        # the gate.
        side_fp = snap.get("graph_fingerprint")
        if side_fp is not None:
            growth = [dict(s) for s in snap.get("growth", [])]
            fp0 = self._graph_fingerprint()
            if fp0 == side_fp:
                self._growth_history = growth
            elif fp0 == snap.get("graph_fingerprint_base"):
                for step in growth:
                    self.graph = graph_mod.grow(
                        self.graph, int(step["n_new"]),  # graftlint: ignore[host-sync-in-loop] -- sidecar JSON scalar, already host
                        node_capacity=int(step["n_pad"]))  # graftlint: ignore[host-sync-in-loop] -- sidecar JSON scalar, already host
                self._graph_fp = None
                self._growth_history = growth
                if self._graph_fingerprint() != side_fp:
                    raise GraphMismatch(
                        f"checkpoint trail at {self._store.directory!r} "
                        "records graph mutations beyond growth (edge "
                        "deltas); replaying the recorded growth onto "
                        "this construction does not reproduce the "
                        "trail's graph — reconstruct the mutated graph "
                        "(persist it with sim/checkpoint.save_graph) or "
                        "pass resume=False to discard the trail",
                        expected=side_fp, got=self._graph_fingerprint(),
                        directory=self._store.directory)
                self._m_capacity.set(float(self.graph.n_nodes_padded))
                # Coverage denominators must see the REGROWN live set:
                # _n_live was computed from the constructed graph, and
                # a stale value would report coverage against the
                # pre-growth overlay (divergent vs an uninterrupted
                # run — the crash-storm campaign caught exactly this).
                n_live = int(np.sum(np.asarray(self.graph.node_mask)))
                with self._cond:
                    self._n_live = n_live
                if spans.current_tracer() is not None:
                    spans.emit("serve_resume_regrow",
                               steps=len(growth),
                               n_pad=int(self.graph.n_nodes_padded))
            else:
                raise GraphMismatch(
                    f"checkpoint trail at {self._store.directory!r} was "
                    f"written against a different overlay (recorded "
                    f"fingerprint {side_fp}, constructed graph "
                    f"{fp0}) — construct with the graph the trail "
                    "belongs to, or pass resume=False to discard it",
                    expected=side_fp, got=fp0,
                    directory=self._store.directory)
        template = self._template()
        try:
            state, key, rnd, msgs = ckpt.load(path, template)
        except (ckpt.CheckpointCorrupt, OSError):
            # The referenced entry is damaged/missing: the sidecar pair
            # is unusable as a unit — fresh start. (A ValueError —
            # treedef mismatch, i.e. a different protocol — propagates
            # as the caller error it is, like the shape check below.)
            self._clear_trail()
            return False
        # ckpt.load validates the treedef only, and MessageBatch is
        # all-array fields — a trail written at a DIFFERENT capacity or
        # graph size would load "successfully" with wrong shapes and
        # wedge the service later (host budget vs device lanes disagree,
        # XLA shape errors mid-chunk). A config mismatch is a caller
        # error; silently discarding the trail would lose real tickets.
        for got, want in zip(jax.tree_util.tree_leaves(state),
                             jax.tree_util.tree_leaves(template)):
            if got.shape != want.shape or got.dtype != want.dtype:
                raise ValueError(
                    f"checkpoint trail at {self._store.directory!r} was "
                    "written by a service with a different capacity or "
                    f"graph (stored leaf {got.shape}/{got.dtype} vs "
                    f"configured {want.shape}/{want.dtype}) — construct "
                    "with the same config, or pass resume=False to "
                    "discard the trail")
        self._batch = jax.device_put(state)
        self._base_key = key
        # Construction is single-threaded, but the control-plane state
        # restored here is lock-guarded everywhere else — keep the
        # discipline uniform rather than special-casing __init__.
        with self._cond:
            self._round = int(rnd)
            self._messages = int(msgs)
            self._tick = int(snap.get("tick", 0))
            self._next_ticket = int(snap.get("next_ticket", 0))
            self._queue = [str(t) for t in snap.get("queue", [])]
            self._lane_ticket = {int(k): str(v)
                                 for k, v in snap.get("lanes", {}).items()}
            # Merge, don't replace: tenants added to quotas AFTER the
            # trail was written must start at their configured burst
            # (absent from the snapshot), and restored levels never
            # exceed a since-shrunk burst.
            restored = {str(k): float(v)
                        for k, v in snap.get("buckets", {}).items()}
            buckets = {t: b for t, (_, b) in self._quotas.items()}
            for k, v in restored.items():
                buckets[k] = min(v, buckets[k]) if k in buckets else v
            self._buckets = buckets
            self._admit_budget = int(snap.get("admit_budget",
                                              self._admit_budget))
            self._counts.update({k: int(v)
                                 for k, v in snap.get("counts", {}).items()})
            self._done_order = [str(t) for t in snap.get("done_order", [])]
            self._latencies = [float(x) for x in snap.get("latencies", [])]
            self._tickets = {str(tid): dict(rec)
                             for tid, rec in snap.get("tickets", {}).items()}
            # graftdur: the seqno this pair covers — the journal-suffix
            # replay starts right past it (built by __init__ once the
            # journal is constructed).
            self._j_acked = int(snap.get("journal_seqno", 0))
            # Failover fencing: adopt the trail's epoch unless the
            # caller pinned one (promote() pins observed+1).
            if not self._epoch_pinned:
                self._epoch = int(snap.get("epoch", 0))
            running = dict(self._lane_ticket)
        # Lanes admitted in the checkpoint but not running (harvested
        # done / cancelled, not yet recycled when the checkpoint landed)
        # queue for the first tick's retire — zero lanes leak.
        admitted = np.flatnonzero(np.asarray(self._batch.admitted)).tolist()
        self._retire_ready = [lane for lane in admitted
                              if lane not in running]
        return True

    # ---------------------------------------------------------------- HTTP

    def handle_http(self, method: str, path: str,
                    body: Optional[dict]) -> Optional[Tuple[int, dict]]:
        """The duck-typed httpd seam (telemetry/httpd.py): claim the
        serving endpoints, return ``None`` for everything else.

        - ``POST /submit`` (JSON body) or ``GET /submit?source=N`` —
          202 ``{"ticket", "status"}``, 429 with the structured reject
          on shed, 400 on caller errors, 503 when closed;
        - ``GET /poll/<ticket>`` — the record, or 404;
        - ``POST /cancel/<ticket>`` — ``{"cancelled": bool}``;
        - ``GET /stats`` — the :meth:`stats` document.
        """
        parsed = urllib.parse.urlparse(path)
        route = parsed.path.rstrip("/") or "/"
        if route == "/stats" and method == "GET":
            return 200, self.stats()
        if route == "/submit" and method in ("GET", "POST"):
            args: Dict[str, Any] = {}
            if method == "GET":
                q = urllib.parse.parse_qs(parsed.query)
                if "source" in q:
                    args["source"] = q["source"][0]
                if "target_coverage" in q:
                    args["target_coverage"] = q["target_coverage"][0]
                if "tenant" in q:
                    args["tenant"] = q["tenant"][0]
            else:
                args = dict(body or {})
            if "source" not in args:
                return 400, {"error": "submit needs a source node id"}
            try:
                tid = self.submit(
                    int(args["source"]),
                    target_coverage=float(
                        args.get("target_coverage", 0.99)),
                    tenant=str(args.get("tenant", "default")))
            except DurabilityLost as e:
                # Durability loss is a SERVER fault, not client load:
                # 503 (retry elsewhere / after repair), never a 429
                # back-off hint.
                return 503, e.to_dict()
            except Rejected as e:
                return 429, e.to_dict()
            except ServiceClosed as e:
                return 503, {"error": str(e)}
            except (TypeError, ValueError) as e:
                return 400, {"error": str(e)}
            return 202, {"ticket": tid, "status": "queued"}
        if route.startswith("/poll/") and method == "GET":
            rec = self.poll(route[len("/poll/"):])
            if rec is None:
                return 404, {"error": "unknown ticket"}
            return 200, rec
        if route.startswith("/cancel/") and method == "POST":
            try:
                ok = self.cancel(route[len("/cancel/"):])
            except DurabilityLost as e:
                return 503, e.to_dict()
            return 200, {"cancelled": ok}
        return None
