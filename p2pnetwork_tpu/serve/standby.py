"""graftdur hot-standby failover: tail the trail, promote with a fence.

A :class:`Standby` is the warm half of a primary/standby pair. It holds
everything needed to BECOME the service — the overlay graph and the
service construction kwargs — but constructs nothing expensive until
promotion. While the primary is alive the standby :meth:`Standby.refresh`\\ es
cheaply: it reads the sidecar JSON and scans the journal segments
(stdlib file reads, no jax, no device memory), so an operator loop can
poll replication lag (``journal_last_seq - journal_seqno``) at any
cadence without disturbing the primary's trail.

:meth:`Standby.promote` is the failover edge. It constructs a
:class:`~p2pnetwork_tpu.serve.service.SimService` over the shared trail
with ``resume=True`` and ``epoch = observed + 1``, then immediately
forces a checkpoint — publishing the incremented fencing token in the
sidecar. From that instant the trail belongs to the new epoch: a zombie
primary (presumed dead, actually wedged) that wakes up and tries to
publish its own boundary pair reads the sidecar token first and gets a
typed :class:`~p2pnetwork_tpu.serve.service.FencedEpoch` — its store
entry never lands, so split-brain is impossible by construction rather
than by timeout tuning.

Promotion inherits the full graftdur resume contract: the promoted
service restores the newest consistent (checkpoint, sidecar) pair and
queues the journal suffix past ``journal_seqno`` for replay, so every
ticket the dead primary ACKNOWLEDGED — including ones journaled after
its last boundary — survives the failover with the same ticket ids.

The standby does NOT fence the primary while merely refreshing: reads
are invisible. Only :meth:`promote` writes, and only through the same
checkpoint path the primary uses — one publication discipline, one
fencing rule.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from p2pnetwork_tpu.serve.journal import read_records
from p2pnetwork_tpu.serve.service import _SIDECAR, SimService

__all__ = ["Standby"]


class Standby:
    """A warm standby for one service trail (see module doc).

    Parameters
    ----------
    graph:
        The overlay the primary serves — promotion constructs the
        replacement service over it (the resume path's graph-identity
        gate checks it against the trail's recorded fingerprint).
    directory:
        The shared trail directory (the primary's ``store=``): sidecar,
        checkpoint entries and journal segments all live here.
    **service_kwargs:
        Forwarded verbatim to :class:`SimService` at promotion —
        capacity, quotas, checkpoint cadence, journal fsync policy —
        so the promoted service runs the primary's configuration.
        ``store``/``resume``/``epoch`` are owned by the standby and
        must not be passed.
    """

    def __init__(self, graph, directory: str, **service_kwargs: Any):
        for owned in ("store", "resume", "epoch"):
            if owned in service_kwargs:
                raise ValueError(
                    f"Standby owns the {owned!r} kwarg (it resumes the "
                    "shared trail with an incremented fencing epoch); "
                    "pass only service configuration")
        self.graph = graph
        self.directory = os.path.abspath(directory)
        self.service_kwargs: Dict[str, Any] = dict(service_kwargs)
        self._last: Optional[dict] = None

    # ------------------------------------------------------------ tailing

    def refresh(self) -> dict:
        """One cheap replication-lag observation of the shared trail.

        Pure reads (sidecar JSON + journal segment scan); safe to call
        at any cadence while the primary is alive. Returns::

            {"epoch", "tick", "journal_seqno", "checkpoint_file",
             "tickets", "journal_last_seq", "replay_pending",
             "corrupt_tail"}

        where ``replay_pending`` is how many acknowledged intents a
        promotion right now would replay past the pair (the standby's
        "how far behind is the sidecar" number), and missing-sidecar
        fields are 0/None (an empty trail promotes to a fresh service
        at epoch 1).
        """
        side: Dict[str, Any] = {}
        try:
            with open(os.path.join(self.directory, _SIDECAR),
                      "r", encoding="utf-8") as f:
                loaded = json.load(f)
            if isinstance(loaded, dict):
                side = loaded
        except (OSError, ValueError):
            pass
        records, corrupt = read_records(self.directory)
        covered = int(side.get("journal_seqno", 0) or 0)
        obs = {
            "epoch": int(side.get("epoch", 0) or 0),
            "tick": int(side.get("tick", 0) or 0),
            "journal_seqno": covered,
            "checkpoint_file": side.get("checkpoint_file"),
            "tickets": len(side.get("tickets", {}) or {}),
            "journal_last_seq": (int(records[-1]["seq"])
                                 if records else 0),
            "replay_pending": sum(1 for r in records
                                  if int(r["seq"]) > covered),
            "corrupt_tail": int(corrupt),
        }
        self._last = obs
        return obs

    @property
    def last_observation(self) -> Optional[dict]:
        """The most recent :meth:`refresh` result (``None`` before the
        first), for operators logging lag between polls."""
        return None if self._last is None else dict(self._last)

    # ---------------------------------------------------------- promotion

    def promote(self) -> SimService:
        """Become the service: resume the trail at ``observed epoch +
        1`` and publish the fencing token immediately.

        Returns the promoted (not yet started) service. After this
        returns, the zombie primary's next checkpoint attempt raises
        :class:`~p2pnetwork_tpu.serve.service.FencedEpoch` — the token
        is already in the sidecar, published through the same atomic
        rename discipline as every boundary pair.
        """
        obs = self.refresh()
        svc = SimService(self.graph, store=self.directory, resume=True,
                         epoch=int(obs["epoch"]) + 1,
                         **self.service_kwargs)
        try:
            # The promoted pair both claims the trail (token) and
            # compacts the replayed suffix it covers.
            svc.checkpoint()
        except BaseException:
            svc.close()
            raise
        return svc
