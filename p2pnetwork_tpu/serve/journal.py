"""graftdur write-ahead journal: the serving plane's sub-boundary
durability log.

The checkpoint pair (store entry + sidecar) is boundary-granular: a
SIGKILL between tick boundaries loses every intent acknowledged since
the last pair. This module closes that window with an append-only,
CRC-per-record, segment-rotated journal of every admission-plane intent
(submit / cancel / shed / grow / apply_delta). The contract:

- an intent is ACKNOWLEDGED only after its record is appended (the
  service appends inside the same critical section that applies the
  intent, before returning to the caller);
- records carry monotonic seqnos; the sidecar records the seqno its
  pair covers (``journal_seqno``), so resume = restore the pair, then
  replay exactly the journal records with ``seq > journal_seqno``;
- replay is torn-tail tolerant: a record whose length/CRC does not
  check out truncates the scan — a kill mid-append costs exactly the
  one record that was never acknowledged, never a parse error;
- segments rotate at checkpoint boundaries and closed segments whose
  records are all covered by the published pair are deleted
  (compaction): the journal holds a bounded suffix, not history.

Record wire format (little-endian)::

    u32 payload_len | u32 crc32(payload) | payload

with the payload a compact sorted-keys JSON object
``{"seq", "epoch", "kind", "tick", ...per-kind fields}``. Appends go
through an unbuffered fd (every ``write`` reaches the page cache
immediately), so a SIGKILL after an append cannot lose the record;
``fsync`` policy only decides what a POWER LOSS can take:
``"record"`` syncs per append (strongest, slowest), ``"tick"`` syncs
once per driver tick (:meth:`Journal.tick_barrier` — the default;
bounded by one tick of intents), ``"off"`` never syncs (page cache
only — still SIGKILL-proof, not power-loss-proof).

A constructed :class:`Journal` never appends to a pre-existing segment
(whose tail may be torn): it scans what is there, remembers the
recovered records for the service's replay, and opens a FRESH segment
for its own appends — seqnos continue from the last intact record.

The ``fault_hook`` seam is the crash-storm campaign's injection point:
a callable receiving ``(event, seq)`` at ``"append_begin"`` /
``"append_mid"`` (between the header and payload writes — a kill here
leaves a genuinely torn record) / ``"append_end"`` / ``"fsync"``. The
hook may SIGKILL the process (subprocess soaks), raise a simulated-kill
exception (in-process tests), or raise ``OSError`` (disk-full
injection). Any exception out of an append marks the journal failed —
the segment tail may be torn, so further appends would land records a
replay can never reach — and the owning service flips to its
``DurabilityLost`` shedding mode.

Stdlib-only (no jax): the crash-storm parent process scans journals of
dead children through :func:`read_records` without touching devices.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from p2pnetwork_tpu import telemetry

__all__ = ["Journal", "read_records", "clear_segments",
           "FSYNC_POLICIES", "RECORD_KINDS"]

_HEADER = struct.Struct("<II")

#: Admission-plane intent kinds a journal records.
RECORD_KINDS = ("submit", "cancel", "shed", "grow", "delta")

#: What a power loss may take: "record" fsyncs every append, "tick"
#: once per driver tick (default), "off" never (page cache only — a
#: SIGKILL still loses nothing; see the module docstring).
FSYNC_POLICIES = ("record", "tick", "off")


def _segment_name(index: int) -> str:
    return f"journal_{index:06d}.wal"


def _segment_paths(directory: str) -> List[Tuple[int, str]]:
    """``(index, path)`` for every journal segment, index-ordered."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("journal_") and name.endswith(".wal")):
            continue
        try:
            idx = int(name[len("journal_"):-len(".wal")])
        except ValueError:
            continue
        out.append((idx, os.path.join(directory, name)))
    out.sort()
    return out


def _scan_segment(path: str) -> Tuple[List[dict], int]:
    """Parse one segment: ``(records, corrupt)`` where ``corrupt`` is 1
    when the scan stopped at a torn/corrupt record (everything after it
    is unreachable — record boundaries are length-prefixed)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return [], 1
    records: List[dict] = []
    off = 0
    while off < len(blob):
        if off + _HEADER.size > len(blob):
            return records, 1  # torn header
        length, crc = _HEADER.unpack_from(blob, off)
        start = off + _HEADER.size
        end = start + length
        if end > len(blob):
            return records, 1  # torn payload
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            return records, 1  # bit rot / overwritten tail
        try:
            doc = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return records, 1
        if not isinstance(doc, dict) or "seq" not in doc:
            return records, 1
        records.append(doc)
        off = end
    return records, 0


def read_records(directory: str) -> Tuple[List[dict], int]:
    """Scan every segment under ``directory`` in order: ``(records,
    corrupt_tail)``. Truncates at the first corrupt record — and, since
    seqnos are contiguous by construction, refuses to leap a gap (a
    segment whose first record does not continue the sequence marks
    everything from it on unrecoverable). Pure read: touches no file
    for writing, creates nothing — safe on a dead service's trail."""
    records: List[dict] = []
    corrupt = 0
    expect: Optional[int] = None
    for _, path in _segment_paths(directory):
        segment, torn = _scan_segment(path)
        for doc in segment:
            seq = int(doc["seq"])
            if expect is not None and seq != expect:
                return records, corrupt + 1
            records.append(doc)
            expect = seq + 1
        corrupt += torn
        if torn:
            # Records beyond a torn segment cannot be contiguous with
            # the recovered prefix (the torn record ate a seqno) — and
            # the next constructed Journal already refused to append
            # after a torn tail, so in practice there is nothing there.
            break
    return records, corrupt


def clear_segments(directory: str) -> None:
    """Delete every journal segment under ``directory`` (fresh-start /
    ``resume=False`` semantics; the service's ``_clear_trail``)."""
    for _, path in _segment_paths(directory):
        try:
            os.unlink(path)
        except OSError:
            pass


class Journal:
    """One directory's write-ahead intent journal (see module doc).

    Parameters
    ----------
    directory:
        Where segments live — the service passes its checkpoint store
        directory, so pair + journal travel as one trail.
    fsync:
        One of :data:`FSYNC_POLICIES` (default ``"tick"``).
    fault_hook:
        Optional ``(event, seq)`` callable, the crash/fault injection
        seam (see module doc). Settable after construction too.
    registry:
        Telemetry registry for the ``serve_journal_*`` families
        (default: the process default registry).
    """

    def __init__(self, directory: str, *, fsync: str = "tick",
                 fault_hook: Optional[Callable[[str, int], None]] = None,
                 registry: Optional[telemetry.Registry] = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy {fsync!r} not in {FSYNC_POLICIES}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fsync_policy = fsync
        self.fault_hook = fault_hook
        self.epoch = 0
        self._failed: Optional[str] = None
        self._closed = False
        self._synced = True       # nothing unsynced yet
        self._appended = 0
        self._bytes = 0
        self._fsyncs = 0
        # Recover what a previous life left: records for the service's
        # replay, per-segment last-seqnos for compaction.
        records, corrupt = read_records(self.directory)
        self._recovered = records
        self._corrupt_tail = corrupt
        #: Closed segments (recovered ones included): index ->
        #: (path, last_seq or None when empty/unreadable).
        self._closed_segments: Dict[int, Tuple[str, Optional[int]]] = {}
        # Map each recovered record to its segment for last-seq
        # bookkeeping: re-scan per segment (cheap — already page-hot).
        max_idx = -1
        for idx, path in _segment_paths(self.directory):
            seg, _ = _scan_segment(path)
            last = int(seg[-1]["seq"]) if seg else None
            self._closed_segments[idx] = (path, last)
            max_idx = idx
        last_seq = int(records[-1]["seq"]) if records else 0
        self._next_seq = last_seq + 1
        # Fresh segment for this life's appends (lazy-opened: an idle
        # service creates no file).
        self._cur_index = max_idx + 1
        self._cur_count = 0
        self._cur_last: Optional[int] = None
        self._fd = None
        reg = registry if registry is not None \
            else telemetry.default_registry()
        self._m_appends = reg.counter(
            "serve_journal_appends_total",
            "Admission-plane intent records appended to the write-ahead "
            "journal, by kind.", ("kind",))
        self._m_bytes = reg.counter(
            "serve_journal_bytes_total",
            "Bytes appended to the write-ahead journal (headers "
            "included).")
        self._m_fsyncs = reg.counter(
            "serve_journal_fsyncs_total",
            "fsync barriers issued by the journal (per-record policy "
            "syncs every append; per-tick syncs once per dirty tick).")
        self._m_segments = reg.gauge(
            "serve_journal_segments",
            "Live journal segment files (rotated at checkpoint "
            "boundaries, compacted once the pair covers them).")
        self._m_segments.set(float(len(self._closed_segments)))

    # ---------------------------------------------------------- recovery

    def records(self) -> List[dict]:
        """The records recovered at construction (the replay suffix
        source). Copies — callers may mutate freely."""
        return [dict(r) for r in self._recovered]

    @property
    def last_seq(self) -> int:
        """Seqno of the last appended (or recovered) record; 0 when the
        journal has never held one."""
        return self._next_seq - 1

    @property
    def failed(self) -> Optional[str]:
        """Why this journal refuses appends, or ``None`` while healthy."""
        return self._failed

    # ---------------------------------------------------------- appending

    def _hook(self, event: str, seq: int) -> None:
        if self.fault_hook is not None:
            self.fault_hook(event, seq)

    def _ensure_open(self):
        if self._fd is None:
            # O_EXCL claim with retry: two journal instances over one
            # directory (a promoted standby plus a not-yet-dead zombie
            # primary) must never interleave writes into one segment
            # file — each claims its own, and the seq-continuity check
            # in read_records truncates at the first divergence.
            while True:
                path = os.path.join(self.directory,
                                    _segment_name(self._cur_index))
                try:
                    raw = os.open(path,
                                  os.O_WRONLY | os.O_CREAT | os.O_EXCL
                                  | getattr(os, "O_APPEND", 0), 0o644)
                    break
                except FileExistsError:
                    self._cur_index += 1
            # Unbuffered: every write reaches the kernel immediately, so
            # an appended record survives SIGKILL without any fsync
            # (fsync only matters for power loss — module doc).
            self._fd = os.fdopen(raw, "ab", buffering=0)
            self._m_segments.set(
                float(len(self._closed_segments) + 1))
        return self._fd

    def append(self, kind: str, **fields: Any) -> int:
        """Durably append one intent record; returns its seqno. Raises
        ``OSError`` when the journal is failed/closed or the write
        fails — at which point the record is NOT acknowledged (the tail
        may be torn) and the journal refuses further appends."""
        if self._closed:
            raise OSError(f"journal at {self.directory!r} is closed")
        if self._failed is not None:
            raise OSError(
                f"journal at {self.directory!r} failed previously "
                f"({self._failed}); the segment tail may be torn")
        seq = self._next_seq
        doc = {"seq": seq, "epoch": int(self.epoch), "kind": str(kind)}
        doc.update(fields)
        payload = json.dumps(doc, sort_keys=True,
                             separators=(",", ":")).encode("utf-8")
        header = _HEADER.pack(len(payload), zlib.crc32(payload))
        try:
            fd = self._ensure_open()
            self._hook("append_begin", seq)
            fd.write(header)
            self._hook("append_mid", seq)
            fd.write(payload)
            self._hook("append_end", seq)
            if self.fsync_policy == "record":
                self._do_fsync()
            else:
                self._synced = False
        except BaseException as e:
            # OSError (real or injected disk-full) or a simulated-kill
            # exception: either way bytes may be torn mid-record.
            self._failed = f"{type(e).__name__}: {e}"
            raise
        self._next_seq = seq + 1
        self._cur_count += 1
        self._cur_last = seq
        self._appended += 1
        self._bytes += len(header) + len(payload)
        self._m_appends.labels(str(kind)).inc()
        self._m_bytes.inc(len(header) + len(payload))
        return seq

    def _do_fsync(self) -> None:
        self._hook("fsync", self._next_seq)
        os.fsync(self._fd.fileno())
        self._fsyncs += 1
        self._synced = True
        self._m_fsyncs.inc()

    def tick_barrier(self) -> None:
        """The per-tick durability barrier: under the ``"tick"`` policy,
        fsync once if anything was appended since the last barrier.
        No-op under ``"record"`` (already synced) and ``"off"``."""
        if (self.fsync_policy != "tick" or self._synced
                or self._fd is None or self._failed is not None):
            return
        try:
            self._do_fsync()
        except OSError as e:
            self._failed = f"{type(e).__name__}: {e}"
            raise

    # -------------------------------------------- rotation and compaction

    def rotate(self) -> None:
        """Close the current segment (if it holds records) and start a
        fresh one — called at checkpoint boundaries so compaction works
        on whole segments the new pair covers."""
        if self._fd is None:
            return
        if self._cur_count == 0:
            return  # nothing in it; keep appending here
        path = os.path.join(self.directory,
                            _segment_name(self._cur_index))
        try:
            self._fd.close()
        except OSError:
            pass
        self._closed_segments[self._cur_index] = (path, self._cur_last)
        self._fd = None
        self._cur_index += 1
        self._cur_count = 0
        self._cur_last = None
        self._m_segments.set(float(len(self._closed_segments)))

    def compact(self, covered_seq: int) -> None:
        """Delete closed segments entirely covered by the published
        pair (``last record seq <= covered_seq``) plus empty ones.
        Segments holding any record beyond ``covered_seq`` — e.g.
        journaled-but-unapplied mutations — survive for replay."""
        covered_seq = int(covered_seq)
        for idx in sorted(self._closed_segments):
            path, last = self._closed_segments[idx]
            if last is not None and last > covered_seq:
                continue
            try:
                os.unlink(path)
            except OSError:
                continue  # keep the bookkeeping; retry next boundary
            del self._closed_segments[idx]
        open_seg = 0 if self._fd is None else 1
        self._m_segments.set(
            float(len(self._closed_segments) + open_seg))

    # ------------------------------------------------------------- admin

    def stats(self) -> dict:
        """The ``/stats`` durability sub-document."""
        return {
            "fsync_policy": self.fsync_policy,
            "last_seq": self.last_seq,
            "appended": self._appended,
            "appended_bytes": self._bytes,
            "fsyncs": self._fsyncs,
            "segments": len(self._closed_segments)
            + (0 if self._fd is None else 1),
            "recovered": len(self._recovered),
            "corrupt_tail": self._corrupt_tail,
            "failed": self._failed,
        }

    def reset(self) -> None:
        """Fresh start: drop every segment and recovered record, seqnos
        restart at 1 (``resume=False`` / damaged-trail semantics)."""
        self.close()
        clear_segments(self.directory)
        self._recovered = []
        self._corrupt_tail = 0
        self._closed_segments = {}
        self._next_seq = 1
        self._cur_index = 0
        self._cur_count = 0
        self._cur_last = None
        self._failed = None
        self._closed = False
        self._synced = True
        self._m_segments.set(0.0)

    def close(self) -> None:
        """Close the append fd (final fsync under ``"tick"`` first).
        Idempotent; a closed journal refuses appends."""
        if self._fd is not None:
            if (self.fsync_policy == "tick" and not self._synced
                    and self._failed is None):
                try:
                    self._do_fsync()
                except OSError:
                    pass  # closing anyway; the trail ends here
            try:
                self._fd.close()
            except OSError:
                pass
            self._closed_segments[self._cur_index] = (
                os.path.join(self.directory,
                             _segment_name(self._cur_index)),
                self._cur_last)
            self._fd = None
        self._closed = True
