"""Typed configuration for both backends.

The reference has no config system at all — configuration is six constructor
parameters plus two mutable attributes [ref: p2pnetwork/node.py:32, :70-73]
(SURVEY.md section 5 "Config / flag system"). We keep that ethos: small typed
dataclasses with defaults chosen for parity, no argparse/env/yaml machinery.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class NodeConfig:
    """Tunables of the sockets backend (defaults = reference behavior)."""

    #: Bytes per receive call [ref: nodeconnection.py:196].
    recv_chunk: int = 4096
    #: Bound on the un-framed receive buffer (fixes SURVEY section 2.3.3;
    #: the reference buffer is unbounded, nodeconnection.py:206).
    max_recv_buffer: int = 64 * 1024 * 1024
    #: Bound on the per-connection outbound write buffer. The reference's
    #: blocking sendall gave natural backpressure; under asyncio a peer that
    #: stops reading would otherwise buffer without limit. Exceeding the
    #: bound closes the connection (same policy as a send failure).
    max_send_buffer: int = 16 * 1024 * 1024
    #: TCP connect + handshake timeout [ref: 10 s socket timeouts,
    #: node.py:97, nodeconnection.py:47].
    connect_timeout: float = 10.0
    #: Seconds between reconnect-registry checks. The reference piggybacks the
    #: check on every accept-loop tick [ref: node.py:265]; a dedicated timer is
    #: the event-loop equivalent. This is the tick FLOOR: per-entry
    #: exponential backoff (below) decides which entries actually retry on
    #: a given tick.
    reconnect_interval: float = 0.5
    #: First-retry delay of the per-entry reconnect backoff. The reference
    #: retries every dead peer at the fixed tick cadence forever; here each
    #: entry backs off with decorrelated jitter — delay_{n+1} drawn uniform
    #: from [base, 3 * delay_n], capped — so a fleet reconnecting after a
    #: peer restart does not stampede it in lockstep.
    reconnect_backoff_base: float = 0.5
    #: Cap on the per-entry backoff delay.
    reconnect_backoff_max: float = 30.0
    #: Listen backlog [ref: listen(1), node.py:98 — raised here deliberately].
    listen_backlog: int = 16
    #: Default text encoding for str/dict payloads.
    encoding: str = "utf-8"
    #: Frame delimiting: "eot" (reference-compatible 0x04 delimiter; raw
    #: bytes containing 0x04 corrupt framing, wire.py) or "length"
    #: (4-byte length prefix — safe for arbitrary binary, both peers must
    #: opt in; no reference interop).
    framing: str = "eot"

    def __post_init__(self):
        # Fail at construction, not deep inside per-connection setup where
        # the error would surface as a generic connection failure.
        if self.framing not in ("eot", "length"):
            raise ValueError(
                f"unknown framing mode: {self.framing!r} "
                f"(choose 'eot' or 'length')"
            )
        if self.reconnect_backoff_base <= 0:
            raise ValueError("reconnect_backoff_base must be positive")
        if self.reconnect_backoff_max < self.reconnect_backoff_base:
            raise ValueError(
                "reconnect_backoff_max must be >= reconnect_backoff_base")


@dataclasses.dataclass
class TopologyConfig:
    """Which random graph to build (see sim/graph.py generators)."""

    kind: str = "watts_strogatz"  # erdos_renyi | barabasi_albert | watts_strogatz | ring | chord | kademlia | complete
    n_nodes: int = 1024
    #: erdos_renyi: edge probability; watts_strogatz: rewire probability.
    p: float = 0.01
    #: barabasi_albert: edges per new node; watts_strogatz: ring degree;
    #: kademlia: bucket width.
    k: int = 10
    seed: int = 0


#: Valid MeshConfig.comm values — parallel/sharded.COMM_BACKENDS plus
#: "auto". A literal on purpose (config stays importable without jax);
#: pinned equal to sharded's tuple by tests/test_ring.py.
COMM_CHOICES = ("ppermute", "pallas", "auto")


@dataclasses.dataclass
class MeshConfig:
    """TPU mesh layout for the sharded propagation path.

    ``shards`` is the number of graph partitions laid out along the ring
    (axis name ``"shards"``); cross-shard edges resolve via ppermute rotation
    over that axis (ICI-friendly; see parallel/sharded.py).
    """

    shards: int = 1
    axis_name: str = "shards"
    #: Halo-exchange backend of the ring path: "ppermute" (XLA
    #: collective-permute), "pallas" (async remote-copy DMA kernels,
    #: ops/pallas_ring.py — overlaps the ICI hop with shard-local
    #: propagation), or "auto" (pallas on TPU, ppermute elsewhere —
    #: parallel/auto.resolve_comm).
    comm: str = "ppermute"

    def __post_init__(self):
        if self.comm not in COMM_CHOICES:
            raise ValueError(
                f"unknown comm backend: {self.comm!r} "
                f"(choose one of {COMM_CHOICES})")


@dataclasses.dataclass
class SimConfig:
    """One simulation run = topology + protocol + schedule + mesh."""

    topology: TopologyConfig = dataclasses.field(default_factory=TopologyConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    #: Maximum rounds to run (static bound for lax.scan / while_loop).
    max_rounds: int = 64
    #: Stop when this fraction of nodes has been covered (flood) — device-side
    #: early exit via lax.while_loop.
    coverage_target: float = 0.99
    seed: int = 0
