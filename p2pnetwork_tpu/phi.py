"""Phi-accrual failure detection over the sockets backend.

The reference's only liveness signal is TCP noticing a dead socket —
up to its 10-second timeout late, and silent about DEGRADING peers
[ref: p2pnetwork/nodeconnection.py:47, node.py:97]. The modern answer
(Hayashibara et al. 2004; Cassandra's and Akka's detector) replaces the
binary alive/dead verdict with a CONTINUOUS suspicion level: learn each
peer's heartbeat inter-arrival distribution, and report

    phi(peer) = -log10( P(a heartbeat would take this long) )

so phi 1 means "this gap happens 1 in 10 times", phi 8 "1 in 10^8 —
it's gone". The threshold becomes an application policy knob (how many
false positives per true detection you'll pay), and a peer on a slow
link EARNS a wider distribution instead of flapping a fixed timeout.

:class:`PhiAccrualNode`:

- :meth:`tick` broadcasts one heartbeat (app-chosen cadence, like
  CoordinateNode's pings); inbound heartbeats update the per-peer
  inter-arrival window (mean/variance over the last ``window``
  arrivals);
- :meth:`phi` reads the current suspicion for a peer;
  :meth:`suspected` applies a threshold; :meth:`suspicion_levels`
  snapshots every peer;
- the sim backend's :class:`~p2pnetwork_tpu.models.detector.
  FailureDetector` is the batched counterpart (ping/ack with a count
  threshold); this is the wall-clock, per-connection form.

The estimator is the logistic normal-tail approximation (as deployed in
Akka — it never underflows, so phi grows smoothly however long the
silence) with a standard-deviation floor of ``max(min_std, 0.1·mean)``:
a perfectly regular heartbeat stream must not estimate sigma ~ 0 and
alarm on one scheduler jitter. Heartbeats are consumed by the detector
and never reach ``node_message`` subclass traffic.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Dict, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

HB_KEY = "_phi_hb"


class _ArrivalWindow:
    """Inter-arrival statistics over the last ``window`` heartbeats."""

    __slots__ = ("intervals", "last")

    def __init__(self, window: int):
        self.intervals: deque = deque(maxlen=window)
        self.last: Optional[float] = None

    def record(self, now: float) -> None:
        if self.last is not None:
            self.intervals.append(now - self.last)
        self.last = now

    def snapshot(self):
        """``(intervals tuple, last arrival)`` — copied so the estimator
        math runs OUTSIDE the detector lock (graftlint open-call
        discipline: hold the lock to copy, compute after release)."""
        return tuple(self.intervals), self.last


def _mean_std(intervals, min_std: float):
    """Mean/stddev of an interval snapshot, or None with no data."""
    if not intervals:
        return None
    m = sum(intervals) / len(intervals)
    var = sum((x - m) ** 2 for x in intervals) / len(intervals)
    # The floor is RELATIVE to the cadence as well as absolute: a
    # perfectly regular 1 Hz stream must not estimate sigma ~ 0 and
    # saturate suspicion one jitter past the mean.
    return m, max(math.sqrt(var), 0.1 * m, min_std)


def _phi_from(elapsed: float, mean: float, std: float) -> float:
    """-log10 of the upper-tail probability of a gap >= elapsed, via the
    logistic approximation of the normal CDF (Hayashibara's estimator as
    deployed in Akka): p = e / (1 + e) with e = exp(-z (1.5976 +
    0.070566 z^2)). Unlike erfc it never underflows — for large z the
    log-tail continues analytically, so phi keeps growing smoothly with
    the silence instead of clipping at a floor."""
    z = (elapsed - mean) / std
    a = z * (1.5976 + 0.070566 * z * z)
    if a < -30.0:
        return 0.0  # gap far below the mean: p ~ 1
    if a > 30.0:
        return a / math.log(10.0)  # p ~ e^-a, exactly the log tail
    e = math.exp(-a)
    return -math.log10(e / (1.0 + e))


class PhiAccrualNode(Node):
    """A :class:`Node` with adaptive, continuous peer suspicion — and,
    when ``quarantine_threshold`` is set, a quarantine -> probe -> readmit
    lifecycle driven by it:

    - a connected peer whose phi exceeds ``quarantine_threshold`` is
      QUARANTINED: excluded from application broadcasts
      (:meth:`send_to_nodes`) but NOT disconnected — heartbeats from
      :meth:`tick` keep probing it;
    - when its heartbeats resume and phi falls below
      ``readmit_threshold`` (default: half the quarantine threshold —
      hysteresis, so a peer hovering at the threshold does not flap), it
      is READMITTED to broadcasts;
    - a peer quarantined longer than ``evict_after`` seconds (``None`` =
      never) is EVICTED: its connection is closed, handing the address to
      the reconnect registry / application policy.

    Transitions are evaluated on every :meth:`tick` (or explicitly via
    :meth:`check_quarantine`), dispatched as ``node_quarantined`` /
    ``node_readmitted`` events, and counted in the
    ``p2p_quarantine_transitions_total{node, transition}`` family with the
    current count in the ``p2p_quarantined_peers`` gauge."""

    def __init__(self, *args, window: int = 100, min_std: float = 0.01,
                 quarantine_threshold: Optional[float] = None,
                 readmit_threshold: Optional[float] = None,
                 evict_after: Optional[float] = None,
                 **kwargs):
        if readmit_threshold is None:
            readmit_threshold = (quarantine_threshold / 2.0
                                 if quarantine_threshold is not None else None)
        if (quarantine_threshold is not None
                and readmit_threshold >= quarantine_threshold):
            # Inverted hysteresis would flap quarantine/readmit every
            # sweep; validated before the base class binds the socket.
            raise ValueError(
                "readmit_threshold must be below quarantine_threshold")
        super().__init__(*args, **kwargs)
        self.window = window
        self.min_std = min_std
        self.quarantine_threshold = quarantine_threshold
        self.readmit_threshold = readmit_threshold
        self.evict_after = evict_after
        self._arrivals: Dict[str, _ArrivalWindow] = {}
        #: peer id -> monotonic time it entered quarantine.
        self._quarantined: Dict[str, float] = {}
        #: bumped under the lock on every quarantine-set mutation;
        #: _publish_quarantined uses it to publish the gauge OUTSIDE the
        #: lock without letting racing publishers strand a stale value.
        self._quarantine_gen = 0
        # Heartbeats append on the event loop while phi()/suspected()
        # read from monitoring threads; an unguarded deque iteration
        # mid-append raises "deque mutated during iteration".
        self._phi_lock = concurrency.lock()
        self._m_phi = self.telemetry.gauge(
            "p2p_phi_suspicion",
            "Phi-accrual suspicion level per peer (refreshed on "
            "suspicion_levels/phi reads; 0 = healthy or no verdict).",
            ("node", "peer"))
        self._m_heartbeats = self.telemetry.counter(
            "p2p_heartbeats_received_total",
            "Inbound phi-accrual heartbeats consumed by the detector.",
            ("node",)).labels(self.id)
        self._m_quarantined = self.telemetry.gauge(
            "p2p_quarantined_peers",
            "Peers currently quarantined by the phi lifecycle.",
            ("node",)).labels(self.id)
        self._m_transitions = self.telemetry.counter(
            "p2p_quarantine_transitions_total",
            "Phi quarantine lifecycle transitions "
            "(quarantine | readmit | evict).",
            ("node", "transition"))

    # ------------------------------------------------------------ app API

    def tick(self) -> None:
        """Heartbeat every peer and evaluate quarantine transitions
        (thread-safe). Call at the cadence your deployment chooses; the
        detector learns it. Heartbeats go to quarantined peers too —
        they are the PROBE that lets a recovering peer earn readmission."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")
        loop.call_soon_threadsafe(self._tick_on_loop)

    def _tick_on_loop(self) -> None:
        for conn in self.all_nodes:
            self.send_to_node(conn, {HB_KEY: 1})
        if self.quarantine_threshold is not None:
            self.check_quarantine()

    def phi(self, peer_id: str, now: Optional[float] = None) -> float:
        """Current suspicion of ``peer_id``: 0.0 while the stream is
        healthy (or still warming up — no verdict without data),
        climbing without bound as the silence stretches."""
        with self._phi_lock:
            w = self._arrivals.get(peer_id)
            if w is None or w.last is None:
                return 0.0
            intervals, last = w.snapshot()
        # Estimator math runs outside the lock on the copied window, so a
        # hundred-peer suspicion sweep never stalls the heartbeat path.
        stats = _mean_std(intervals, self.min_std)
        if stats is None:
            return 0.0
        now = time.monotonic() if now is None else now
        value = _phi_from(now - last, *stats)
        self._m_phi.labels(self.id, peer_id).set(value)
        return value

    def suspected(self, peer_id: str, threshold: float = 8.0,
                  now: Optional[float] = None) -> bool:
        """Suspicion policy: phi above ``threshold`` (8 ~ a gap this
        long happens less than 1 in 10^8 heartbeats)."""
        return self.phi(peer_id, now) > threshold

    def suspicion_levels(self) -> Dict[str, float]:
        """Snapshot of phi for every peer that has ever heartbeated."""
        now = time.monotonic()
        with self._phi_lock:
            peers = list(self._arrivals)
        return {pid: self.phi(pid, now) for pid in peers}

    # ---------------------------------------------------------- quarantine

    def quarantined(self) -> Dict[str, float]:
        """Currently quarantined peers: ``{peer_id: seconds in quarantine}``."""
        now = time.monotonic()
        with self._phi_lock:
            return {pid: now - since for pid, since in self._quarantined.items()}

    def is_quarantined(self, peer_id: str) -> bool:
        with self._phi_lock:
            return peer_id in self._quarantined

    def check_quarantine(self, now: Optional[float] = None) -> None:
        """Evaluate quarantine / readmit / evict for every connected peer.

        No-op unless ``quarantine_threshold`` is set. Runs on every
        :meth:`tick`; callable directly (e.g. with a synthetic ``now``)
        from tests or monitoring threads."""
        if self.quarantine_threshold is None:
            return
        now = time.monotonic() if now is None else now
        for conn in list(self.all_nodes):
            pid = conn.id
            value = self.phi(pid, now)
            with self._phi_lock:
                since = self._quarantined.get(pid)
            if since is None:
                if value > self.quarantine_threshold:
                    self._transition(pid, "quarantine", now)
                continue
            if value < self.readmit_threshold:
                # Fresh heartbeats pulled phi back down: the probe
                # succeeded, the peer has earned its way back in.
                self._transition(pid, "readmit", now)
            elif (self.evict_after is not None
                  and now - since > self.evict_after):
                if self._transition(pid, "evict", now):
                    conn.stop()

    def _transition(self, peer_id: str, transition: str, now: float) -> bool:
        """Atomically apply one lifecycle transition; returns whether it
        took effect. The state check and the mutation share one lock
        acquisition so concurrent sweeps (loop tick + a monitoring
        thread) cannot double-fire a transition or evict a peer the
        other sweep just readmitted."""
        with self._phi_lock:
            if transition == "quarantine":
                if peer_id in self._quarantined:
                    return False  # another sweep got here first
                self._quarantined[peer_id] = now
            else:
                if self._quarantined.pop(peer_id, None) is None:
                    return False
            self._quarantine_gen += 1
        # Gauge publication happens OUTSIDE the lock (the metric takes its
        # own lock — graftlint's open-call discipline); the generation
        # protocol in _publish_quarantined keeps racing publishers from
        # stranding a stale count.
        self._publish_quarantined()
        self._m_transitions.labels(self.id, transition).inc()
        event = {"quarantine": "node_quarantined",
                 "readmit": "node_readmitted",
                 "evict": "node_evicted"}[transition]
        self.debug_print(f"{event}: {peer_id}")
        self._dispatch(event, None, {"peer": peer_id})
        return True

    def _publish_quarantined(self) -> None:
        """Publish the quarantined-peer count without holding the detector
        lock across the metric call. Snapshot (count, generation) under
        the lock, set the gauge outside it, and re-check the generation:
        whichever publisher observes the final generation also publishes
        the final count, so interleaved publishers cannot strand a stale
        gauge — the property the old set-under-the-lock bought, without
        nesting the metric's lock under ours."""
        while True:
            with self._phi_lock:
                gen = self._quarantine_gen
                count = len(self._quarantined)
            self._m_quarantined.set(count)
            with self._phi_lock:
                if self._quarantine_gen == gen:
                    return

    def send_to_nodes(self, data, exclude=None, compression="none") -> None:
        """Broadcast excluding quarantined peers: a suspected-degrading
        peer stops receiving application traffic (the graceful eviction)
        while heartbeat probes from :meth:`tick` — which bypass this by
        sending per-connection — keep testing it for readmission."""
        exclude = list(exclude or [])
        if self.quarantine_threshold is not None:
            with self._phi_lock:
                bad = set(self._quarantined)
            if bad:
                exclude += [c for c in self.all_nodes
                            if c.id in bad and c not in exclude]
        super().send_to_nodes(data, exclude, compression)

    # ------------------------------------------------------ interception

    def _record_heartbeat(self, peer_id: str,
                          now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._phi_lock:
            self._arrivals.setdefault(
                peer_id, _ArrivalWindow(self.window)).record(now)
        self._m_heartbeats.inc()

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict) and HB_KEY in data:
            self._record_heartbeat(node.id)
            return
        super().node_message(node, data)

    def node_disconnected(self, node: NodeConnection) -> None:
        # TCP already rendered its verdict: drop the window so a
        # reconnecting peer starts a fresh estimate instead of being
        # judged against its pre-crash rhythm. Quarantine state goes with
        # it — a reconnecting peer starts active, not pre-condemned.
        with self._phi_lock:
            self._arrivals.pop(node.id, None)
            self._quarantined.pop(node.id, None)
            self._quarantine_gen += 1
        self._publish_quarantined()
        # Prune (not zero) the gauge: a departed peer must not leave a
        # forever-sample behind — under churn that cardinality only grows.
        self._m_phi.remove(self.id, node.id)
        super().node_disconnected(node)
