"""Phi-accrual failure detection over the sockets backend.

The reference's only liveness signal is TCP noticing a dead socket —
up to its 10-second timeout late, and silent about DEGRADING peers
[ref: p2pnetwork/nodeconnection.py:47, node.py:97]. The modern answer
(Hayashibara et al. 2004; Cassandra's and Akka's detector) replaces the
binary alive/dead verdict with a CONTINUOUS suspicion level: learn each
peer's heartbeat inter-arrival distribution, and report

    phi(peer) = -log10( P(a heartbeat would take this long) )

so phi 1 means "this gap happens 1 in 10 times", phi 8 "1 in 10^8 —
it's gone". The threshold becomes an application policy knob (how many
false positives per true detection you'll pay), and a peer on a slow
link EARNS a wider distribution instead of flapping a fixed timeout.

:class:`PhiAccrualNode`:

- :meth:`tick` broadcasts one heartbeat (app-chosen cadence, like
  CoordinateNode's pings); inbound heartbeats update the per-peer
  inter-arrival window (mean/variance over the last ``window``
  arrivals);
- :meth:`phi` reads the current suspicion for a peer;
  :meth:`suspected` applies a threshold; :meth:`suspicion_levels`
  snapshots every peer;
- the sim backend's :class:`~p2pnetwork_tpu.models.detector.
  FailureDetector` is the batched counterpart (ping/ack with a count
  threshold); this is the wall-clock, per-connection form.

The estimator is the logistic normal-tail approximation (as deployed in
Akka — it never underflows, so phi grows smoothly however long the
silence) with a standard-deviation floor of ``max(min_std, 0.1·mean)``:
a perfectly regular heartbeat stream must not estimate sigma ~ 0 and
alarm on one scheduler jitter. Heartbeats are consumed by the detector
and never reach ``node_message`` subclass traffic.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, Optional

from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

HB_KEY = "_phi_hb"


class _ArrivalWindow:
    """Inter-arrival statistics over the last ``window`` heartbeats."""

    __slots__ = ("intervals", "last")

    def __init__(self, window: int):
        self.intervals: deque = deque(maxlen=window)
        self.last: Optional[float] = None

    def record(self, now: float) -> None:
        if self.last is not None:
            self.intervals.append(now - self.last)
        self.last = now

    def mean_std(self, min_std: float):
        if not self.intervals:
            return None
        m = sum(self.intervals) / len(self.intervals)
        var = sum((x - m) ** 2 for x in self.intervals) / len(self.intervals)
        # The floor is RELATIVE to the cadence as well as absolute: a
        # perfectly regular 1 Hz stream must not estimate sigma ~ 0 and
        # saturate suspicion one jitter past the mean.
        return m, max(math.sqrt(var), 0.1 * m, min_std)


def _phi_from(elapsed: float, mean: float, std: float) -> float:
    """-log10 of the upper-tail probability of a gap >= elapsed, via the
    logistic approximation of the normal CDF (Hayashibara's estimator as
    deployed in Akka): p = e / (1 + e) with e = exp(-z (1.5976 +
    0.070566 z^2)). Unlike erfc it never underflows — for large z the
    log-tail continues analytically, so phi keeps growing smoothly with
    the silence instead of clipping at a floor."""
    z = (elapsed - mean) / std
    a = z * (1.5976 + 0.070566 * z * z)
    if a < -30.0:
        return 0.0  # gap far below the mean: p ~ 1
    if a > 30.0:
        return a / math.log(10.0)  # p ~ e^-a, exactly the log tail
    e = math.exp(-a)
    return -math.log10(e / (1.0 + e))


class PhiAccrualNode(Node):
    """A :class:`Node` with adaptive, continuous peer suspicion."""

    def __init__(self, *args, window: int = 100, min_std: float = 0.01,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.window = window
        self.min_std = min_std
        self._arrivals: Dict[str, _ArrivalWindow] = {}
        # Heartbeats append on the event loop while phi()/suspected()
        # read from monitoring threads; an unguarded deque iteration
        # mid-append raises "deque mutated during iteration".
        self._phi_lock = threading.Lock()
        self._m_phi = self.telemetry.gauge(
            "p2p_phi_suspicion",
            "Phi-accrual suspicion level per peer (refreshed on "
            "suspicion_levels/phi reads; 0 = healthy or no verdict).",
            ("node", "peer"))
        self._m_heartbeats = self.telemetry.counter(
            "p2p_heartbeats_received_total",
            "Inbound phi-accrual heartbeats consumed by the detector.",
            ("node",)).labels(self.id)

    # ------------------------------------------------------------ app API

    def tick(self) -> None:
        """Broadcast one heartbeat to every peer (thread-safe). Call at
        the cadence your deployment chooses; the detector learns it."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")
        loop.call_soon_threadsafe(
            lambda: self.send_to_nodes({HB_KEY: 1}))

    def phi(self, peer_id: str, now: Optional[float] = None) -> float:
        """Current suspicion of ``peer_id``: 0.0 while the stream is
        healthy (or still warming up — no verdict without data),
        climbing without bound as the silence stretches."""
        with self._phi_lock:
            w = self._arrivals.get(peer_id)
            if w is None or w.last is None:
                return 0.0
            stats = w.mean_std(self.min_std)
            last = w.last
        if stats is None:
            return 0.0
        now = time.monotonic() if now is None else now
        value = _phi_from(now - last, *stats)
        self._m_phi.labels(self.id, peer_id).set(value)
        return value

    def suspected(self, peer_id: str, threshold: float = 8.0,
                  now: Optional[float] = None) -> bool:
        """Suspicion policy: phi above ``threshold`` (8 ~ a gap this
        long happens less than 1 in 10^8 heartbeats)."""
        return self.phi(peer_id, now) > threshold

    def suspicion_levels(self) -> Dict[str, float]:
        """Snapshot of phi for every peer that has ever heartbeated."""
        now = time.monotonic()
        with self._phi_lock:
            peers = list(self._arrivals)
        return {pid: self.phi(pid, now) for pid in peers}

    # ------------------------------------------------------ interception

    def _record_heartbeat(self, peer_id: str,
                          now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._phi_lock:
            self._arrivals.setdefault(
                peer_id, _ArrivalWindow(self.window)).record(now)
        self._m_heartbeats.inc()

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict) and HB_KEY in data:
            self._record_heartbeat(node.id)
            return
        super().node_message(node, data)

    def node_disconnected(self, node: NodeConnection) -> None:
        # TCP already rendered its verdict: drop the window so a
        # reconnecting peer starts a fresh estimate instead of being
        # judged against its pre-crash rhythm.
        with self._phi_lock:
            self._arrivals.pop(node.id, None)
        # Prune (not zero) the gauge: a departed peer must not leave a
        # forever-sample behind — under churn that cardinality only grows.
        self._m_phi.remove(self.id, node.id)
        super().node_disconnected(node)
