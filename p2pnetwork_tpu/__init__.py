"""tpu-p2p: a TPU-native peer-to-peer network framework.

Two backends behind one extension API (SURVEY.md section 7):

- **sockets backend** (`Node`, `NodeConnection`): real TCP networking with
  behavior and wire-format parity with the reference
  (pj8912/python-p2p-network) — extend-a-Node-class or callback API, the
  ten-event vocabulary, broadcast with exclude lists, str/dict/bytes payloads,
  zlib/bzip2/lzma compression, connection limits, reconnect policies.
- **sim backend** (`p2pnetwork_tpu.sim`, `p2pnetwork_tpu.models`): the new
  pillar — populations of simulated nodes as JAX arrays, protocol rounds as
  batched graph propagation (`lax.scan` over segment aggregation), sharded
  across a TPU mesh with ring `ppermute` cross-shard edges
  (`p2pnetwork_tpu.parallel`).

The sim subpackages import JAX; this root module does not, so the sockets
backend works standalone.

Both backends report into one telemetry plane (`p2pnetwork_tpu.telemetry`):
a zero-dep metrics registry (counters / gauges / histograms) with JSONL and
Prometheus exporters — see GETTING_STARTED.md "Observability".

Failure is an injectable input on both backends too: the sim flips
device-side masks (`sim/failures.py`), the sockets backend has a seeded
chaos plane (`p2pnetwork_tpu.chaos`) mirroring the same API name-for-name —
see GETTING_STARTED.md "Fault injection & chaos".

Both disciplines those halves depend on — no silent retraces/host syncs in
the sim, no blocking-under-lock or lock-order hazards in the sockets
backend — are enforced statically by `p2pnetwork_tpu.analysis` (graftlint:
``python -m p2pnetwork_tpu.analysis``) with a runtime ``retrace_guard``
complement — see GETTING_STARTED.md "Static analysis & retrace budgets".
The threaded plane is additionally checked *dynamically*: every
thread/lock/event/queue primitive is constructed through the
`p2pnetwork_tpu.concurrency` seam, and graftrace
(``python -m p2pnetwork_tpu.analysis.race``) explores seeded
deterministic schedules over it with vector-clock happens-before race
detection — see GETTING_STARTED.md "Deterministic concurrency testing".

Long runs survive the hardware they run on via the supervised execution
plane (`p2pnetwork_tpu.supervise`): chunked runs with deadline watchdogs,
atomic auto-checkpoint directories, and bit-exact SIGKILL/preemption
resume — see GETTING_STARTED.md "Supervised runs & crash recovery".
"""

from p2pnetwork_tpu import chaos, supervise, telemetry, wire
from p2pnetwork_tpu.chaos import ChaosPlane
from p2pnetwork_tpu.config import MeshConfig, NodeConfig, SimConfig, TopologyConfig
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection
from p2pnetwork_tpu.causal import CausalNode
from p2pnetwork_tpu.coordnode import CoordinateNode
from p2pnetwork_tpu.crdt import (
    CRDTNode,
    GCounter,
    LWWRegister,
    ORSet,
    PNCounter,
)
from p2pnetwork_tpu.phi import PhiAccrualNode
from p2pnetwork_tpu.securenode import SecureNode
from p2pnetwork_tpu.snapshot import SnapshotNode
from p2pnetwork_tpu.sync import SyncNode
from p2pnetwork_tpu.termination import TerminationNode

__version__ = "0.4.0"

__all__ = [
    "Node",
    "NodeConnection",
    "ChaosPlane",
    "chaos",
    "CausalNode",
    "CoordinateNode",
    "CRDTNode",
    "GCounter",
    "PNCounter",
    "LWWRegister",
    "ORSet",
    "PhiAccrualNode",
    "SecureNode",
    "SnapshotNode",
    "SyncNode",
    "TerminationNode",
    "NodeConfig",
    "SimConfig",
    "TopologyConfig",
    "MeshConfig",
    "supervise",
    "telemetry",
    "wire",
    "__version__",
]
