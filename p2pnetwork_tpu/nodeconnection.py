"""Per-peer transport for the sockets backend.

``NodeConnection`` has the same role and public surface as the reference's
class of the same name [ref: p2pnetwork/nodeconnection.py:9]: it represents
one TCP connection with a peer (inbound or outbound), owns framing /
serialization / compression for that peer, delivers parsed messages upward
through ``main_node.node_message`` [ref: nodeconnection.py:216] and reports
its own death through ``main_node.node_disconnected``
[ref: nodeconnection.py:228].

The concurrency design is deliberately different (SURVEY.md section 7): the
reference runs one OS thread per connection with a 10 ms poll loop
[ref: nodeconnection.py:186-229]; here each connection is an asyncio task on
its owning ``Node``'s event loop — no polling, no per-connection thread, and
no data races because every piece of peer state is only ever touched from
that one loop (the reference mutates shared lists from 3+ thread types with
no locks, SURVEY.md section 2.3.6).

Public surface parity:
- ``send(data, encoding_type='utf-8', compression='none')``
  [ref: nodeconnection.py:107]
- ``stop()`` [ref: nodeconnection.py:162]
- ``set_info/get_info`` and the ``info`` dict [ref: nodeconnection.py:231-235]
- ``id``, ``host``, ``port``, ``main_node``, ``EOT_CHAR``, ``COMPR_CHAR``
  attributes; ``__str__``/``__repr__`` [ref: nodeconnection.py:237-244]
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import time
from typing import Any, Optional, Tuple, Union

from p2pnetwork_tpu import concurrency, wire

#: The transport handed to ``create_new_connection`` — an asyncio stream pair.
StreamPair = Tuple[asyncio.StreamReader, asyncio.StreamWriter]


class NodeConnection:
    """One peer connection: framing, serialization, compression, delivery.

    Constructor signature mirrors the reference factory contract
    [ref: node.py:196-201]: ``(main_node, connection, id, host, port)``, where
    ``connection`` is the transport — an ``(StreamReader, StreamWriter)`` pair
    here instead of a raw socket.
    """

    def __init__(self, main_node, connection: StreamPair, id: str, host: str, port: int):
        self.host = host
        self.port = port
        self.main_node = main_node
        self.reader, self.writer = connection

        # Parity: ids are always strings [ref: nodeconnection.py:35].
        self.id = str(id)

        # Exposed for parity with the reference's per-instance constants
        # [ref: nodeconnection.py:38-41]; the codec itself lives in wire.py.
        self.EOT_CHAR = wire.EOT_CHAR
        self.COMPR_CHAR = wire.COMPR_CHAR

        # Per-connection key/value store [ref: nodeconnection.py:44, :231-235].
        self.info: dict = {}

        # Parity flag; set by stop(). An event so non-loop threads can
        # observe it, like the reference's flag [ref: nodeconnection.py:32];
        # seam-constructed so graftrace can instrument it.
        self.terminate_flag = concurrency.event()

        self._decoder = wire.make_decoder(
            main_node.config.framing,
            max_buffer=main_node.config.max_recv_buffer,
        )
        self._task: Optional[asyncio.Task] = None
        # Set when the transport is known bad (send failure / backpressure
        # trip): stop() then force-aborts instead of draining gracefully.
        self._abort = False

        # Per-peer byte accounting (telemetry/): children resolved once per
        # connection, not per frame — .labels() is a dict lookup under a
        # lock and this is the transport hot path.
        self._m_bytes_sent = main_node._m_bytes_sent.labels(
            main_node.id, self.id)
        self._m_bytes_recv = main_node._m_bytes_recv.labels(
            main_node.id, self.id)

        self.main_node.debug_print(
            f"NodeConnection.send: Started with client ({self.id}) '{self.host}:{self.port}'"
        )

    # ------------------------------------------------------------------ send

    def compress(self, data: bytes, compression: str) -> Optional[bytes]:
        """Compress ``data``; returns ``None`` for an unknown algorithm.

        Behavior parity with [ref: nodeconnection.py:53-82] including the
        debug-printed compression ratio [ref: nodeconnection.py:80]; the codec
        wire format lives in :func:`wire.compress`.
        """
        self.main_node.debug_print(f"{self.id}:compress:{compression}")
        try:
            compressed = wire.compress(data, compression)
        except wire.UnknownCompressionError:
            self.main_node.debug_print(f"{self.id}:compress:Unknown compression")
            return None
        if data:
            ratio = int(10000 * len(compressed) / len(data)) / 100
            self.main_node.debug_print(f"{self.id}:compress:compression:{ratio}%")
        return compressed

    def decompress(self, compressed: bytes) -> bytes:
        """Decompress a tagged payload [ref: nodeconnection.py:84-105].

        The node's receive-buffer bound doubles as the decompression
        OUTPUT bound: a frame small enough to pass the framing decoder
        must not be allowed to expand past what the node would ever have
        accepted on the wire (amplification-bomb containment the
        reference lacks). A blob past the bound raises
        ``wire.DecompressionBombError``, which the recv loop counts as a
        receive error and drops — never a partial expansion, never
        compressed bytes delivered as if they were the message."""
        return wire.decompress(compressed,
                               max_output=self.main_node.config.max_recv_buffer)

    def parse_packet(self, packet: bytes) -> Union[str, dict, bytes]:
        """Decode one de-framed packet [ref: nodeconnection.py:167-184].

        Routes through ``self.decompress`` so subclasses overriding the codec
        (e.g. to add encryption) affect the receive path, as in the reference
        [ref: nodeconnection.py:171]. Under ``framing="length"`` the body
        carries an explicit compression flag byte instead of the sniffable
        trailing marker (wire.py), so arbitrary binary decodes intact."""
        if self.main_node.config.framing == "length":
            if packet[:1] == wire.LENGTH_COMPRESSED:
                return wire.decode_payload(self.decompress(packet[1:]))
            return wire.decode_payload(packet[1:])
        if packet.find(wire.COMPR_CHAR) == len(packet) - 1:
            packet = self.decompress(packet[:-1])
        return wire.decode_payload(packet)

    def send(self, data: Union[str, dict, bytes], encoding_type: Optional[str] = None,
             compression: str = "none") -> None:
        """Serialize, frame and queue ``data`` for transmission.

        Thread-safe: may be called from any thread (the write itself happens
        on the owning node's event loop). ``encoding_type`` defaults to the
        node's ``config.encoding`` (utf-8). Behavior parity with
        [ref: nodeconnection.py:107-160]:

        - str / dict / bytes dispatch (dict as JSON),
        - invalid payload type -> debug message only,
        - compression goes through ``self.compress`` so subclasses can
          override the codec, as in the reference [ref: nodeconnection.py:119];
          an unknown algorithm sends nothing (the reference's silent-drop,
          nodeconnection.py:120-121) but ``message_count_rerr`` is
          incremented (the reference defines that counter and never uses it,
          SURVEY.md section 2.3.7),
        - a transport failure closes the connection (the "issue #19" policy,
          nodeconnection.py:123-126).
        """
        encoding = encoding_type or self.main_node.config.encoding
        try:
            raw = wire.encode_payload(data, encoding)
        except TypeError:
            self.main_node.debug_print(
                "datatype used is not valid please use str, dict (will be send as json) or bytes"
            )
            return
        except Exception as e:
            self.main_node.debug_print(f"nodeconnection send: Error encoding data: {e}")
            self.main_node._record_rerr()
            return
        if compression == "none":
            payload, is_compressed = raw, False
        else:
            blob = self.compress(raw, compression)
            if blob is None:
                self.main_node._record_rerr()
                return
            payload, is_compressed = blob, True
        try:
            frame = wire.wrap_frame(payload, self.main_node.config.framing,
                                    compressed=is_compressed)
        except ValueError as e:  # e.g. body beyond the 4-byte length prefix
            self.main_node.debug_print(f"nodeconnection send: {e}")
            self.main_node._record_rerr()
            return

        loop = self.main_node._loop
        if loop is None or loop.is_closed():
            self.main_node.debug_print("nodeconnection send: node is not running")
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self._write(frame)
        else:
            try:
                loop.call_soon_threadsafe(self._write, frame)
            except RuntimeError:
                self.main_node.debug_print("nodeconnection send: node is not running")

    def _write(self, frame: bytes) -> None:
        """Write one frame on the event loop; failure closes the connection.

        Gates on transport state, not ``terminate_flag``: a send queued
        just before ``stop()`` must still flush during the graceful close
        (stop sets the flag synchronously, but this callback runs before
        stop's close callback on the same loop queue)."""
        if self._abort or self.writer.is_closing():
            return
        try:
            self.writer.write(frame)
            self._m_bytes_sent.inc(len(frame))
            # Backpressure bound: the reference's blocking sendall stalled the
            # sender when the peer stopped reading; asyncio buffers instead.
            # A peer that falls further behind than max_send_buffer is treated
            # as a failed transport (same close-on-failure policy).
            transport = self.writer.transport
            if (transport is not None
                    and transport.get_write_buffer_size() > self.main_node.config.max_send_buffer):
                raise BufferError(
                    f"peer is not reading: write buffer exceeds "
                    f"{self.main_node.config.max_send_buffer} bytes"
                )
        except Exception as e:
            self.main_node.debug_print(f"nodeconnection send: Error sending data to node: {e}")
            self.main_node._record_rerr()
            # Failed transports don't drain: a graceful close would wait on
            # the (possibly never-read) buffer forever, wedging the recv
            # task. Mark for force-abort, then apply the "issue #19"
            # close-on-failure policy [ref: nodeconnection.py:123-126].
            self._abort = True
            self.stop()

    # ------------------------------------------------------- receive lifecycle

    def start(self) -> None:
        """Start the receive task on the owning node's event loop.

        Parity seam with ``thread_client.start()`` [ref: node.py:159, :249];
        callable from the loop itself or from another thread.
        """
        loop = self.main_node._loop
        if loop is None:
            raise RuntimeError("NodeConnection.start: owning node is not running")
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self._task = loop.create_task(self._recv_loop())
        else:
            fut = asyncio.run_coroutine_threadsafe(self._spawn(), loop)
            # Spawning a task is queue-bounded work; if it cannot complete
            # within the connect timeout the loop is wedged, and an
            # unbounded wait here would wedge the caller with it.
            timeout = self.main_node.config.connect_timeout + 1.0
            try:
                fut.result(timeout=timeout)
            except concurrent.futures.TimeoutError:
                fut.cancel()
                raise RuntimeError(
                    f"NodeConnection.start: owning node's event loop did "
                    f"not schedule the receive task within {timeout}s")

    async def _spawn(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._recv_loop())

    async def _recv_loop(self) -> None:
        """Receive chunks, de-frame, parse, deliver upward.

        The asyncio analog of the reference's thread main loop
        [ref: nodeconnection.py:186-229]: on EOF or error the connection is
        closed and ``main_node.node_disconnected(self)`` fires exactly once
        [ref: nodeconnection.py:228].
        """
        node = self.main_node
        try:
            while not self.terminate_flag.is_set():
                chunk = await self.reader.read(node.config.recv_chunk)
                if not chunk:  # EOF — peer closed
                    break
                self._m_bytes_recv.inc(len(chunk))
                try:
                    for packet in self._decoder.feed(chunk):
                        node._record_recv()  # [ref: nodeconnection.py:215]
                        t0 = time.perf_counter()
                        try:
                            node.node_message(self, self.parse_packet(packet))
                            node._m_handle.observe(time.perf_counter() - t0)
                        except Exception as e:
                            # Neither a crashing user handler nor a bad
                            # frame (DecompressionBombError included) may
                            # kill the transport (in the reference either
                            # kills the recv thread without cleanup); the
                            # frame is dropped and counted.
                            node._record_rerr()
                            node.debug_print(
                                f"parse/handler error, frame dropped: {e!r}")
                except wire.FrameOverflowError as e:
                    node._record_rerr()
                    node.debug_print(f"NodeConnection: {e}")
                    break
        except asyncio.CancelledError:
            pass
        except Exception as e:
            node.debug_print("Unexpected error")
            node.debug_print(str(e))
        finally:
            self.terminate_flag.set()
            try:
                self.writer.close()
            except Exception:
                pass
            node.node_disconnected(self)  # [ref: nodeconnection.py:228]
            node.debug_print("NodeConnection: Stopped")

    def stop(self) -> None:
        """Request connection termination [ref: nodeconnection.py:162-165].

        Thread-safe. Closing the transport wakes the receive task (its read
        returns EOF), which then runs the disconnect epilogue.
        """
        self.terminate_flag.set()
        loop = self.main_node._loop
        if loop is None or loop.is_closed():
            return

        def _close():
            try:
                transport = self.writer.transport
                if self._abort and transport is not None:
                    # The transport already failed (send error or
                    # max_send_buffer trip): a graceful close would wait
                    # for a buffer the peer is not draining, so the recv
                    # task would never see EOF. Drop the buffer and close.
                    transport.abort()
                else:
                    # Graceful: flush anything queued, then FIN — in-flight
                    # frames sent just before stop() still reach the peer.
                    self.writer.close()
            except Exception:
                pass

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            _close()
        else:
            try:
                loop.call_soon_threadsafe(_close)
            except RuntimeError:
                pass  # loop closed between the check and the post — idempotent

    async def wait_closed(self, timeout: float = 10.0) -> None:
        """Await full termination of the receive task (loop-side helper).

        Bounded: a peer that never drains our graceful close would
        otherwise pin the recv task (no EOF) and wedge ``Node.stop()``;
        past ``timeout`` the transport is force-aborted."""
        if self._task is None:
            return
        try:
            await asyncio.wait_for(asyncio.shield(self._task), timeout)
        except asyncio.TimeoutError:
            try:
                transport = self.writer.transport
                if transport is not None:
                    transport.abort()
            except Exception:
                pass
            try:
                await self._task
            except Exception:
                pass
        except Exception:
            pass

    # ------------------------------------------------------------------ info

    def set_info(self, key: str, value: Any) -> None:
        """Store auxiliary data on this connection [ref: nodeconnection.py:231]."""
        self.info[key] = value

    def get_info(self, key: str) -> Any:
        """Fetch auxiliary data from this connection [ref: nodeconnection.py:234]."""
        return self.info[key]

    # ------------------------------------------------------------------ repr

    def __str__(self) -> str:
        return "NodeConnection: {}:{} <-> {}:{} ({})".format(
            self.main_node.host, self.main_node.port, self.host, self.port, self.id
        )

    def __repr__(self) -> str:
        return "<NodeConnection: Node {}:{} <-> Connection {}:{}>".format(
            self.main_node.host, self.main_node.port, self.host, self.port
        )
