"""Wire format of the sockets backend.

Byte-compatible with the reference implementation so that a tpu-p2p node can
interoperate with a live reference node on the same network:

- Frames are delimited by an EOT byte (``0x04``)
  [ref: p2pnetwork/nodeconnection.py:38].
- Compressed frames carry a trailing COMPR marker byte (``0x02``) just before
  the EOT [ref: nodeconnection.py:41, :121].
- A compressed payload is ``base64(compressed_bytes + algo_tag)`` where the
  tag is the literal suffix ``b'zlib'`` / ``b'bzip2'`` / ``b'lzma'``
  [ref: nodeconnection.py:63-70, :92-99].
- Payloads are ``str`` (utf-8), ``dict`` (JSON) or raw ``bytes``
  [ref: nodeconnection.py:114-156].
- Parse order on receive: strip + decompress if marked, try utf-8 decode, try
  JSON, fall back to str, fall back to raw bytes
  [ref: nodeconnection.py:167-184].

Everything in this module is a pure function (plus one small stateful stream
decoder) so the wire format is unit-testable without sockets.

Deliberate fixes over the reference (SURVEY.md section 2.3):
- empty frames (EOT at buffer position 0) are consumed instead of wedging the
  stream forever [ref bug: nodeconnection.py:211],
- the receive buffer is bounded; exceeding it raises ``FrameOverflowError``
  instead of growing without limit [ref bug: nodeconnection.py:206].

Inherited wire-format limitation (kept for interop): raw ``bytes`` payloads
containing the EOT byte ``0x04`` corrupt framing, exactly as in the
reference. Sending such payloads with ``compression=`` enabled is safe —
the base64 alphabet contains no control bytes. Deployments that do not need
reference interop can instead opt into ``framing="length"``
(``NodeConfig.framing``): 4-byte big-endian length prefix + one compression
flag byte + payload, which carries arbitrary binary safely — no delimiter to
corrupt and no marker byte to sniff (a raw payload may freely end in 0x02).
Both peers must use the same framing; the default stays ``"eot"``
(reference-compatible).
"""

from __future__ import annotations

import base64
import bz2
import json
import lzma
import zlib
from typing import Iterator, Optional, Union

Payload = Union[str, dict, list, bytes]

#: End-of-transmission frame delimiter [ref: nodeconnection.py:38].
EOT_CHAR = b"\x04"
#: Marker appended to compressed payloads [ref: nodeconnection.py:41].
COMPR_CHAR = b"\x02"

#: algorithm name -> (compress fn, wire tag suffix) [ref: nodeconnection.py:63-70]
_CODECS = {
    "zlib": (lambda raw: zlib.compress(raw, 6), b"zlib"),
    "bzip2": (bz2.compress, b"bzip2"),
    "lzma": (lzma.compress, b"lzma"),
}


class UnknownCompressionError(ValueError):
    """Raised when an unknown compression algorithm name is requested."""


class FrameOverflowError(RuntimeError):
    """Raised when the stream buffer exceeds its bound without an EOT."""


def compress(raw: bytes, algorithm: str) -> bytes:
    """Compress ``raw`` and tag it with the algorithm suffix, base64-encoded.

    Wire format parity: ``base64(compressed + tag)`` [ref:
    nodeconnection.py:63-70]. Unlike the reference (which returns ``None`` and
    silently sends nothing, nodeconnection.py:72-74), an unknown algorithm
    raises :class:`UnknownCompressionError` so callers can surface the error.
    """
    try:
        fn, tag = _CODECS[algorithm]
    except KeyError:
        raise UnknownCompressionError(
            f"unknown compression algorithm: {algorithm!r} "
            f"(choose from {sorted(_CODECS)} or 'none')"
        ) from None
    return base64.b64encode(fn(raw) + tag)


class DecompressionBombError(ValueError):
    """Decompressed output would exceed the caller's ``max_output`` bound.

    PROPAGATES out of :func:`decompress` (unlike codec failures, which
    fall back to the as-is contract): the caller asked for the bound, so
    containment must be observable — the sockets recv path catches it as
    a receive error (rerr) and drops the frame rather than delivering
    either a partial expansion or compressed bytes masquerading as the
    message."""


def _bounded_decompress(data: bytes, max_output: int, make,
                        multistream: bool) -> bytes:
    """Decompress with a hard output bound via incremental decompressors.

    Semantics parity with the unbounded stdlib functions: bz2/lzma
    concatenate multiple streams (``multistream=True``), zlib returns the
    first stream and ignores trailing bytes. A stream that ends before
    its end-of-stream marker raises EOFError — the same
    codec-failure class the unbounded path raises, so the caller's as-is
    fallback applies; only genuinely over-bound output raises
    :class:`DecompressionBombError`."""
    if max_output <= 0:
        # zlib's max_length=0 means UNLIMITED (bz2/lzma's means "0 bytes"):
        # a zero/negative bound must contain, not silently disable.
        raise DecompressionBombError(
            f"max_output must be positive, got {max_output}")
    out = b""
    while True:
        d = make()
        budget = max_output - len(out)
        chunk = d.decompress(data, max(budget, 0))
        out += chunk
        if not d.eof:
            if len(out) >= max_output:
                raise DecompressionBombError(
                    f"decompressed output exceeds {max_output} bytes")
            raise EOFError("compressed stream ended before end-of-stream")
        data = d.unused_data
        if not multistream or not data:
            return out


def decompress(blob: bytes, max_output: Optional[int] = None) -> bytes:
    """Base64-decode ``blob`` and decompress according to its tag suffix.

    Mirrors the reference's tag sniffing [ref: nodeconnection.py:92-99]: an
    unrecognised tag, or a codec failure, returns the b64-decoded bytes as-is
    [ref: nodeconnection.py:100-101]. Deliberate fix over the reference: its
    b64decode sits outside the try, so a malformed frame carrying the COMPR
    marker raises out of packet parsing [ref bug: nodeconnection.py:91];
    here bytes that aren't base64 at all come back unchanged, honoring the
    as-is contract.

    ``max_output`` bounds the DECOMPRESSED size — without it a ~100 KB
    frame (well inside any receive-buffer bound) can expand to gigabytes
    on the receiving host, an amplification the reference inherits
    unbounded [ref: nodeconnection.py:84-105] and the frame-size bound
    cannot see. Exceeding the bound raises
    :class:`DecompressionBombError` — observable, unlike codec failures,
    because silently delivering the compressed bytes as if they were the
    message would be indistinguishable from a real payload. ``None``
    keeps the historical unbounded behavior; the sockets backend passes
    its receive-buffer bound here (nodeconnection.py ``decompress``).
    """
    try:
        data = base64.b64decode(blob)
    except Exception:
        return blob
    try:
        if data[-4:] == b"zlib":
            if max_output is None:
                return zlib.decompress(data[:-4])
            return _bounded_decompress(data[:-4], max_output,
                                       zlib.decompressobj, False)
        if data[-5:] == b"bzip2":
            if max_output is None:
                return bz2.decompress(data[:-5])
            return _bounded_decompress(data[:-5], max_output,
                                       bz2.BZ2Decompressor, True)
        if data[-4:] == b"lzma":
            if max_output is None:
                return lzma.decompress(data[:-4])
            return _bounded_decompress(data[:-4], max_output,
                                       lzma.LZMADecompressor, True)
    except DecompressionBombError:
        raise
    except Exception:
        pass
    return data


def encode_payload(data: Payload, encoding: str = "utf-8") -> bytes:
    """Serialize a payload by type: str -> text, dict/list -> JSON, bytes raw.

    [ref: nodeconnection.py:114/128/145; JSON for dicts at :131]. Raises
    ``TypeError`` for unsupported types (the reference only debug-prints,
    nodeconnection.py:158-160; callers preserve that behavior at the
    connection layer).
    """
    if isinstance(data, str):
        return data.encode(encoding)
    if isinstance(data, (dict, list)):
        return json.dumps(data).encode(encoding)
    if isinstance(data, (bytes, bytearray)):
        return bytes(data)
    raise TypeError(
        "datatype used is not valid please use str, dict (will be send as "
        f"json) or bytes: got {type(data).__name__}"
    )


#: Length-framing body flag bytes. framing="length" is this framework's
#: own format with no reference compatibility to preserve, so compression
#: is an EXPLICIT leading flag — not the reference's sniffable trailing
#: marker, which silently eats a 0x02 that legitimately ends a raw
#: payload. Body layout (the one released layout of this mode): 1 flag
#: byte + payload; both peers must run the same framework version, as
#: with any non-interop wire format.
LENGTH_PLAIN = b"\x00"
LENGTH_COMPRESSED = b"\x01"


def wrap_frame(payload: bytes, framing: str = "eot",
               compressed: bool = False) -> bytes:
    """Wrap a serialized (and possibly compressed) payload for the wire —
    the single place framing rules, compression marking, and bounds
    checks live; used by :func:`encode_frame` and the connection send
    path alike. ``payload`` is the raw encoded bytes, or the b64 blob
    from :func:`compress` when ``compressed``."""
    if framing == "eot":
        if compressed:
            return payload + COMPR_CHAR + EOT_CHAR
        return payload + EOT_CHAR
    if framing == "length":
        body = (LENGTH_COMPRESSED if compressed else LENGTH_PLAIN) + payload
        if len(body) > 0xFFFFFFFF:
            raise ValueError("frame body exceeds the 4-byte length prefix")
        return len(body).to_bytes(4, "big") + body
    raise ValueError(f"unknown framing mode: {framing!r} "
                     f"(choose 'eot' or 'length')")


def encode_frame(
    data: Payload, encoding: str = "utf-8", compression: str = "none",
    framing: str = "eot",
) -> bytes:
    """Build one on-wire frame.

    ``framing="eot"`` (default): payload [+ COMPR] + EOT — byte-compatible
    with the reference [ref: nodeconnection.py:117 (plain) and :121
    (compressed)]. ``framing="length"``: 4-byte big-endian length prefix +
    flag byte + payload — safe for arbitrary binary (no delimiter to
    corrupt, no marker to sniff), NOT reference-compatible.
    """
    raw = encode_payload(data, encoding)
    if compression == "none":
        return wrap_frame(raw, framing, compressed=False)
    return wrap_frame(compress(raw, compression), framing, compressed=True)


def parse_length_body(body: bytes) -> Payload:
    """Decode one length-framed body (flag byte + payload) — the
    ``framing="length"`` counterpart of :func:`parse_packet`."""
    if body[:1] == LENGTH_COMPRESSED:
        return decode_payload(decompress(body[1:]))
    return decode_payload(body[1:])


def parse_packet(packet: bytes) -> Payload:
    """Decode one de-framed packet back into str / dict / bytes.

    Parse order parity [ref: nodeconnection.py:167-184]: a trailing COMPR
    marker means decompress first; then utf-8 decode; then JSON; falling back
    to the decoded str and finally the raw bytes.
    """
    # Parity: the reference treats a packet as compressed only when the FIRST
    # 0x02 is the last byte [ref: nodeconnection.py:170] — endswith() would
    # misfire on raw-bytes payloads containing an interior 0x02.
    if packet.find(COMPR_CHAR) == len(packet) - 1:
        packet = decompress(packet[:-1])
    return decode_payload(packet)


def decode_payload(packet: bytes) -> Payload:
    """The utf-8 -> JSON -> str -> bytes fallback chain on decompressed bytes
    [ref: nodeconnection.py:173-184]."""
    try:
        text = packet.decode("utf-8")
    except UnicodeDecodeError:
        return packet
    try:
        return json.loads(text)
    except ValueError:
        # JSONDecodeError, but also e.g. the int-digit-limit ValueError that
        # json.loads raises for absurdly long numeric strings.
        return text


class FrameDecoder:
    """Incremental EOT-delimited stream decoder with a bounded buffer.

    Replaces the reference's inline buffer scan [ref: nodeconnection.py:206-218]
    with two deliberate fixes (SURVEY.md section 2.3.2/2.3.3): empty frames are
    consumed (an EOT at position 0 no longer wedges the stream), and the buffer
    is bounded by ``max_buffer`` bytes.
    """

    def __init__(self, max_buffer: int = 64 * 1024 * 1024):
        self.max_buffer = max_buffer
        self._buffer = b""

    def feed(self, chunk: bytes) -> Iterator[bytes]:
        """Feed a received chunk; yield each complete (de-framed) packet."""
        if not chunk:
            return
        self._buffer += chunk
        start = 0
        try:
            while True:
                eot = self._buffer.find(EOT_CHAR, start)
                if eot < 0:
                    break
                yield self._buffer[start:eot]
                start = eot + 1
        finally:
            if start:
                self._buffer = self._buffer[start:]
        if len(self._buffer) > self.max_buffer:
            overflow = len(self._buffer)
            self._buffer = b""
            raise FrameOverflowError(
                f"receive buffer exceeded {self.max_buffer} bytes "
                f"({overflow} buffered) without an EOT delimiter"
            )

    @property
    def pending(self) -> int:
        """Number of buffered bytes not yet terminated by an EOT."""
        return len(self._buffer)


class LengthFrameDecoder:
    """Incremental length-prefixed stream decoder (``framing="length"``).

    Same ``feed``/``pending`` surface as :class:`FrameDecoder`, so the
    connection layer swaps decoders without caring which framing is active.
    A declared frame length beyond ``max_buffer`` is rejected immediately
    (:class:`FrameOverflowError`) — a malicious 4 GiB header cannot make the
    receiver buffer it first.
    """

    _HEADER = 4

    def __init__(self, max_buffer: int = 64 * 1024 * 1024):
        self.max_buffer = max_buffer
        self._buffer = b""

    def feed(self, chunk: bytes) -> Iterator[bytes]:
        """Feed a received chunk; yield each complete frame body."""
        if not chunk:
            return
        self._buffer += chunk
        while len(self._buffer) >= self._HEADER:
            body_len = int.from_bytes(self._buffer[:self._HEADER], "big")
            # Header-inclusive bound: buffered bytes never exceed
            # max_buffer, exactly as advertised.
            if body_len > self.max_buffer - self._HEADER:
                self._buffer = b""
                raise FrameOverflowError(
                    f"declared frame length {body_len} exceeds the "
                    f"{self.max_buffer}-byte receive bound"
                )
            end = self._HEADER + body_len
            if len(self._buffer) < end:
                break
            yield self._buffer[self._HEADER:end]
            self._buffer = self._buffer[end:]

    @property
    def pending(self) -> int:
        """Number of buffered bytes not yet forming a complete frame."""
        return len(self._buffer)


def make_decoder(framing: str, max_buffer: int = 64 * 1024 * 1024):
    """Decoder for a framing mode: ``"eot"`` or ``"length"``."""
    if framing == "eot":
        return FrameDecoder(max_buffer=max_buffer)
    if framing == "length":
        return LengthFrameDecoder(max_buffer=max_buffer)
    raise ValueError(f"unknown framing mode: {framing!r} "
                     f"(choose 'eot' or 'length')")
