"""Stateless per-(walker, edge) uniforms, identical on every backend.

The walker cohort (models/walk.py) draws one uniform per candidate edge
per round. Drawing by ARRAY SLOT (`jax.random.uniform` over the gathered
row) would tie the stream to the memory layout — the sharded ring
(parallel/sharded.py) sees the same edges in different positions on
different shards, so slot-keyed draws could never match the engine. This
module keys the draw by the edge's IDENTITY instead: a mixing hash of
(round key, walker, global sender, global receiver) → f32 in [0, 1).
Any party that can name the edge computes the same number, which is what
makes the sharded walk bit-identical to the engine and invariant to the
shard count.

The mix is a boost-style hash_combine over the inputs followed by the
murmur3 finalizer (fmix32) — not cryptographic, but full-avalanche, and
the statistical quality is pinned by tests (uniform occupancy over a
star hub; KS-style bounds in tests/test_walk.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def edge_uniform(key: jax.Array, walker, sender, receiver) -> jax.Array:
    """f32 uniforms in [0, 1), one per broadcast element of
    ``(walker, sender, receiver)`` under PRNG ``key``.

    Inputs broadcast like jnp operands ([W, 1] walker against [W, slots]
    receivers is the typical shape). int32 inputs are reinterpreted as
    uint32 — negative sentinels hash fine (consumers mask them anyway).
    """
    kd = jax.random.key_data(key).astype(jnp.uint32)
    golden = jnp.uint32(0x9E3779B9)
    h = kd[..., 0] ^ golden
    for v in (kd[..., 1], walker, sender, receiver):
        v = jnp.asarray(v).astype(jnp.uint32)  # graftlint: ignore[host-sync-in-loop] -- 4-way trace-time unroll inside jit; asarray on a tracer is a no-op, not a transfer
        # boost::hash_combine, elementwise over the broadcast shape.
        h = h ^ (v + golden + (h << 6) + (h >> 2))
    # murmur3 fmix32 finalizer: full avalanche.
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    # Top 24 bits -> [0, 1) exactly representable in f32.
    return (h >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1 / (1 << 24))
