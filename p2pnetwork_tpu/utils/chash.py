"""Consistent hashing with virtual nodes — the DHT partitioning rule.

*Which peer owns this key?* — the question under every distributed
store built on overlays like the reference's, where users hand-roll
ownership on top of ``node_message`` routing [ref: README.md:20]. The
classic answer (Karger et al.; the Dynamo/Cassandra partitioner):
hash each node onto a ring at ``vnodes`` points, hash each key once,
and the owner is the first vnode clockwise. Two properties carry the
whole design, and the tests pin both:

- **balance** — with ``v`` vnodes per peer, load concentration drops
  like 1/sqrt(v·n);
- **minimal disruption** — a join/leave moves only the ~1/n slice of
  keys adjacent to the changed peer; every other key keeps its owner
  (the property naive ``hash(key) % n`` lacks entirely).

Pure-function flavor to match the rest of the package: a
:class:`HashRing` is immutable; ``add``/``remove`` return NEW rings, so
"who moved?" is answerable by comparing two rings — which is exactly
what :func:`moved_fraction` does. Hashing is blake2b (stdlib,
deterministic across processes — ids map identically on every peer
with no coordination, the point of the technique).

``owners(keys, k)`` returns k-replica owner lists (distinct peers
walking clockwise), the replication rule DHT stores layer on top.
The ring walk of a bulk lookup is one vectorized numpy
``searchsorted`` over the vnode table; hashing the keys themselves is
per-key blake2b on the host (the honest cost of cross-process-stable
hashes — pre-hash once with :func:`hash_keys` and reuse the positions
when the same key set is resolved repeatedly).
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence, Tuple

import numpy as np

_SPACE = np.uint64(2**64 - 1)


def _h64(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          "big")


def _vnode_points(node_id: str, vnodes: int) -> np.ndarray:
    return np.array(
        [_h64(f"{node_id}#{i}".encode()) for i in range(vnodes)],
        dtype=np.uint64)


def hash_keys(keys: Sequence) -> np.ndarray:
    """u64 ring positions for a batch of keys (str or bytes)."""
    out = np.empty(len(keys), dtype=np.uint64)
    for i, k in enumerate(keys):
        out[i] = _h64(k if isinstance(k, bytes) else str(k).encode())
    return out


class HashRing:
    """Immutable consistent-hash ring over string peer ids."""

    def __init__(self, node_ids: Sequence[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self.node_ids: Tuple[str, ...] = tuple(sorted(set(node_ids)))
        pts, own = [], []
        for idx, nid in enumerate(self.node_ids):
            p = _vnode_points(nid, vnodes)
            pts.append(p)
            own.append(np.full(vnodes, idx, dtype=np.int32))
        if pts:
            points = np.concatenate(pts)
            owners = np.concatenate(own)
            order = np.argsort(points, kind="stable")
            self._points = points[order]
            self._owner_idx = owners[order]
        else:
            self._points = np.empty(0, dtype=np.uint64)
            self._owner_idx = np.empty(0, dtype=np.int32)

    # ------------------------------------------------------------- edits

    def add(self, node_id: str) -> "HashRing":
        return HashRing(self.node_ids + (node_id,), self.vnodes)

    def remove(self, node_id: str) -> "HashRing":
        return HashRing(tuple(i for i in self.node_ids if i != node_id),
                        self.vnodes)

    # ----------------------------------------------------------- lookups

    def owner(self, key) -> str:
        """The peer owning one key."""
        return self.owners_at(hash_keys([key]))[0]

    def owner_indices_at(self, positions: np.ndarray) -> np.ndarray:
        """Index into ``node_ids`` per u64 ring position — the fully
        vectorized bulk path (no Python-object materialization)."""
        if not self.node_ids:
            raise ValueError("empty ring")
        idx = np.searchsorted(self._points, positions, side="left")
        idx = np.where(idx == len(self._points), 0, idx)  # ring wrap
        return self._owner_idx[idx]

    def owners_at(self, positions: np.ndarray) -> List[str]:
        """Owning peer id per u64 ring position."""
        return [self.node_ids[i] for i in self.owner_indices_at(positions)]

    def owners(self, key, k: int = 1) -> List[str]:
        """The first ``k`` DISTINCT peers clockwise from the key — the
        replica set. ``k`` above the peer count returns all peers."""
        if not self.node_ids:
            raise ValueError("empty ring")
        if k <= 0:
            return []
        k = min(k, len(self.node_ids))
        pos = hash_keys([key])[0]
        start = int(np.searchsorted(self._points, pos, side="left"))
        out: List[str] = []
        n = len(self._points)
        for step in range(n):
            nid = self.node_ids[self._owner_idx[(start + step) % n]]
            if nid not in out:
                out.append(nid)
                if len(out) == k:
                    break
        return out

    def load_fractions(self, sample: int = 1 << 16,
                       seed: int = 0) -> dict:
        """Sampled fraction of key space owned per peer."""
        rng = np.random.default_rng(seed)
        pos = rng.integers(0, int(_SPACE), size=sample, dtype=np.uint64)
        idx = self.owner_indices_at(pos)
        counts = np.bincount(idx, minlength=len(self.node_ids))
        return {nid: int(c) / sample
                for nid, c in zip(self.node_ids, counts)}


def moved_fraction(before: HashRing, after: HashRing,
                   sample: int = 1 << 16, seed: int = 0) -> float:
    """Sampled fraction of keys whose owner differs between two rings —
    the disruption metric (consistent hashing's promise: ~1/n per
    single join/leave, against ~1 for modulo hashing)."""
    rng = np.random.default_rng(seed)
    pos = rng.integers(0, int(_SPACE), size=sample, dtype=np.uint64)
    # Owner INDICES are ring-local (the id lists differ); resolve to id
    # strings through one vectorized fancy-index per ring and compare
    # as arrays — no per-sample Python loop.
    a = np.asarray(before.node_ids, dtype=object)[
        before.owner_indices_at(pos)]
    b = np.asarray(after.node_ids, dtype=object)[
        after.owner_indices_at(pos)]
    return float(np.mean(a != b))
