"""Node identity generation.

Parity with the reference scheme [ref: p2pnetwork/node.py:85-90]:
sha512 over host + port + a random integer in [1, 99999999], hex-encoded.
"""

from __future__ import annotations

import hashlib
import random


def generate_id(host: str, port: int, rng: random.Random | None = None) -> str:
    """Generate a unique hex node id [ref: node.py:85-90]."""
    r = rng if rng is not None else random
    digest = hashlib.sha512()
    digest.update((host + str(port) + str(r.randint(1, 99999999))).encode("ascii"))
    return digest.hexdigest()
