"""Structured observability for the sockets backend.

The reference's observability is a debug flag gating prints plus three integer
counters [ref: p2pnetwork/node.py:64-67, :80-83] (SURVEY.md section 5
"Metrics"). We keep the counters (same names, on ``Node``) and add a bounded
structured event log so tests and applications can assert on event history
instead of parsing stdout.

``EventLog`` is one face of the unified telemetry plane (telemetry/):
:meth:`EventLog.to_jsonl` exports history in the shared JSONL schema
(``telemetry.export.event_record`` — ``type: "event"`` lines that interleave
with metric samples in one stream), and ``Node`` mirrors every recorded
event into the registry's ``p2p_events_total`` family.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import IO, Any, Deque, List, Optional, Union

from p2pnetwork_tpu import concurrency


@dataclasses.dataclass(frozen=True)
class EventRecord:
    """One framework event: name, monotonic timestamp, involved peer, data."""

    event: str
    timestamp: float
    peer_id: Optional[str]
    data: Any = None


class EventLog:
    """Bounded, thread-safe in-memory event history."""

    def __init__(self, maxlen: int = 4096):
        self._events: Deque[EventRecord] = collections.deque(maxlen=maxlen)
        self._lock = concurrency.lock()

    def record(self, event: str, peer_id: Optional[str] = None, data: Any = None) -> None:
        rec = EventRecord(event, time.monotonic(), peer_id, data)
        with self._lock:
            self._events.append(rec)

    def snapshot(self) -> List[EventRecord]:
        with self._lock:
            return list(self._events)

    def count(self, event: Optional[str] = None) -> int:
        with self._lock:
            if event is None:
                return len(self._events)
            return sum(1 for e in self._events if e.event == event)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_jsonl(self, sink: Union[str, IO]) -> int:
        """Append the history to ``sink`` (path or file object), one line
        per event in the shared telemetry JSONL schema — the same envelope
        ``telemetry.export.write_jsonl`` gives metric samples, so socket
        events and metrics land in one stream a single parser reads.
        Returns the number of lines written."""
        from p2pnetwork_tpu.telemetry import export

        return export.write_records(
            (export.event_record(e.event, e.timestamp, e.peer_id, e.data)
             for e in self.snapshot()), sink)
