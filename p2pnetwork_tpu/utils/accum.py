"""Wide message accounting without enabling global x64.

The reference's counters are unbounded Python ints [ref: p2pnetwork/
node.py:64-67]; the sim engine's device-side counters are not. With JAX's
default 32-bit mode a 10M-node / 100M-edge run reaches ~1e8 messages per
round, so a few dozen full-frontier rounds silently wrap an int32
accumulator. Enabling ``jax_enable_x64`` globally is the wrong fix — it
flips every default dtype (``jax.random.uniform`` becomes f64, breaking RNG
bit-parity contracts and TPU-unfriendly f64 math everywhere).

Instead: a two-limb accumulator. ``lo`` is uint32 (addition wraps mod 2^32
by definition, and a wrap is detected as ``lo + x < lo``); ``hi`` counts
2^32 carries in int32. Range: 2^63 messages — per-round counts stay int32,
which is structurally safe because a round's message count is bounded by
the directed edge count, and edge indices are int32 already.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

Acc = Tuple[jax.Array, jax.Array]  # (hi: i32, lo: u32)


def zero() -> Acc:
    """A fresh accumulator (loop-carry friendly: two scalars)."""
    return (jnp.int32(0), jnp.uint32(0))


def add(acc: Acc, x: jax.Array) -> Acc:
    """Add a non-negative int32/uint32 scalar; jittable.

    Unsigned overflow is well-defined wraparound, and since ``x < 2^32``
    each add carries at most one: carry happened iff the wrapped sum is
    smaller than either operand.
    """
    hi, lo = acc
    lo2 = lo + x.astype(jnp.uint32)
    return (hi + (lo2 < lo).astype(jnp.int32), lo2)


def value(acc: Acc) -> int:
    """Combine to an exact Python int (host-side; forces a transfer)."""
    hi, lo = acc
    return (int(np.asarray(hi)) << 32) + int(np.uint32(np.asarray(lo)))


def pack_summary(rounds: jax.Array, coverage: jax.Array, acc: Acc,
                 extra=None) -> jax.Array:
    """[rounds, coverage-bits, hi, lo-bits] as one i32[4] — a single
    device->host transfer carries a whole run summary (on tunneled
    backends every extra round trip is milliseconds). Shared by the
    engine's and the sharded path's run-to-coverage loops.

    ``extra`` (optional f32 scalar) appends a fifth slot — the engine
    packs the mean per-round frontier occupancy there; callers that
    don't pass it keep the original i32[4] layout byte for byte."""
    hi, lo = acc
    parts = [
        rounds,
        jax.lax.bitcast_convert_type(coverage, jnp.int32),
        hi,
        jax.lax.bitcast_convert_type(lo, jnp.int32),
    ]
    if extra is not None:
        parts.append(
            jax.lax.bitcast_convert_type(jnp.float32(extra), jnp.int32))
    return jnp.stack(parts)


def unpack_summary(packed) -> dict:
    """Host-side inverse of :func:`pack_summary` (forces the transfer).
    A fifth slot, when present, comes back under ``"extra"``."""
    arr = np.asarray(packed)
    coverage = float(arr[1:2].view(np.float32)[0])
    messages = (int(arr[2]) << 32) + int(arr[3:4].view(np.uint32)[0])
    out = {"rounds": int(arr[0]), "coverage": coverage, "messages": messages}
    if arr.size >= 5:
        out["extra"] = float(arr[4:5].view(np.float32)[0])
    return out


#: Fixed slots of the batch summary ahead of the per-lane vectors.
_BATCH_HEAD = 6


def pack_batch_summary(rounds: jax.Array, active_lanes: jax.Array,
                       completed: jax.Array, acc: Acc, occ_mean: jax.Array,
                       done_words: jax.Array,
                       lane_rounds: jax.Array) -> jax.Array:
    """The batch engine's one-transfer run summary: ``i32[6 + W + B]``.

    Head: ``[global_rounds, active_lanes, completed, hi, lo-bits,
    occupancy-bits]`` — the scalar aggregates in :func:`pack_summary`'s
    spirit. Tail: the PER-LANE vectors the batched plane adds — the
    ``done`` lane flags packed as ``u32[W]`` words (ops/bitset.py lane
    order) and each lane's applied-round count ``i32[B]``. One packed
    vector = one device->host transfer for the whole B-message summary,
    however many messages rode the batch (on tunneled backends every
    extra round trip is milliseconds — B of them would dwarf the run)."""
    hi, lo = acc
    head = jnp.stack([
        rounds.astype(jnp.int32),
        active_lanes.astype(jnp.int32),
        completed.astype(jnp.int32),
        hi,
        jax.lax.bitcast_convert_type(lo, jnp.int32),
        jax.lax.bitcast_convert_type(jnp.float32(occ_mean), jnp.int32),
    ])
    return jnp.concatenate([
        head,
        jax.lax.bitcast_convert_type(done_words, jnp.int32).reshape(-1),
        lane_rounds.astype(jnp.int32),
    ])


def pack_query_summary(rounds: jax.Array, active_lanes: jax.Array,
                       completed: jax.Array, acc: Acc, occ_mean: jax.Array,
                       done_words: jax.Array, lane_rounds: jax.Array,
                       lane_values: jax.Array, *,
                       values_float: bool) -> jax.Array:
    """The query engine's one-transfer run summary:
    ``i32[6 + W + K + K]`` — :func:`pack_batch_summary`'s head and
    per-lane tail plus the query plane's addition: each lane's ANSWER
    (``lane_values``) rides the same packed vector, so a whole
    K-query result set costs one device->host transfer. Answers are
    f32 (bitcast; routing distances, aggregation means) or raw i32
    (DHT cursors — f32 would corrupt node ids past 2^24) per
    ``values_float``, which is static protocol knowledge the unpacker
    must be told again."""
    if values_float:
        vals = jax.lax.bitcast_convert_type(
            lane_values.astype(jnp.float32), jnp.int32)
    else:
        vals = lane_values.astype(jnp.int32)
    return jnp.concatenate([
        pack_batch_summary(rounds, active_lanes, completed, acc, occ_mean,
                           done_words, lane_rounds),
        vals.reshape(-1),
    ])


def unpack_query_summary(packed, capacity: int, *,
                         values_float: bool) -> dict:
    """Host-side inverse of :func:`pack_query_summary` (forces the
    transfer). ``lane_done``/``lane_rounds`` trim to ``capacity`` (the
    done words pad to whole 32-lane blocks); ``lane_values`` comes back
    f32 or i32 per ``values_float``. The head + per-lane core decodes
    through :func:`unpack_batch_summary` — one copy of that layout."""
    arr = np.asarray(packed)
    capacity = int(capacity)
    n_words = -(-capacity // 32)
    core_len = _BATCH_HEAD + n_words + capacity
    out = unpack_batch_summary(arr[:core_len], n_words)
    out["lane_done"] = out["lane_done"][:capacity]
    vals = arr[core_len:]
    out["lane_values"] = (vals.view(np.float32) if values_float
                          else vals.astype(np.int32))
    return out


def unpack_batch_summary(packed, n_words: int) -> dict:
    """Host-side inverse of :func:`pack_batch_summary` (forces the
    transfer). Returns ``rounds`` / ``active_lanes`` / ``completed`` /
    ``messages`` (exact int) / ``occupancy_mean`` plus the per-lane
    ``lane_done`` (bool[B]) and ``lane_rounds`` (i32[B]) vectors."""
    arr = np.asarray(packed)
    messages = (int(arr[3]) << 32) + int(arr[4:5].view(np.uint32)[0])
    done_words = arr[_BATCH_HEAD:_BATCH_HEAD + n_words].view(np.uint32)
    bits = (done_words[:, None] >> np.arange(32, dtype=np.uint32)) & 1
    return {
        "rounds": int(arr[0]),
        "active_lanes": int(arr[1]),
        "completed": int(arr[2]),
        "messages": messages,
        "occupancy_mean": float(arr[5:6].view(np.float32)[0]),
        "lane_done": bits.reshape(-1).astype(bool),
        "lane_rounds": arr[_BATCH_HEAD + n_words:].astype(np.int32),
    }
