"""Small shared utilities: node ids, debug printing, structured event
log, consistent hashing."""

from p2pnetwork_tpu.utils.chash import HashRing, hash_keys, moved_fraction
from p2pnetwork_tpu.utils.ids import generate_id
from p2pnetwork_tpu.utils.logging import EventLog, EventRecord

__all__ = ["generate_id", "EventLog", "EventRecord", "HashRing",
           "hash_keys", "moved_fraction"]
