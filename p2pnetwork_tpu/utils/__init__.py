"""Small shared utilities: node ids, debug printing, structured event log."""

from p2pnetwork_tpu.utils.ids import generate_id
from p2pnetwork_tpu.utils.logging import EventLog, EventRecord

__all__ = ["generate_id", "EventLog", "EventRecord"]
