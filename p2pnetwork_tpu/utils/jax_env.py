"""JAX platform-selection hardening.

In some environments (including this image) a ``sitecustomize`` imports jax
at interpreter startup, which snapshots config defaults before user code —
so ``JAX_PLATFORMS=cpu`` set in the environment can be ignored and backend
discovery may initialize (and hang on) an accelerator plugin. Re-applying
the env var through ``jax.config`` is reliable in either import order.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` from the environment via jax.config.

    No-op when the variable is unset or the backend is already initialized.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platforms)
    except (ImportError, RuntimeError):
        pass
