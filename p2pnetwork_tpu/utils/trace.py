"""Tracing / profiling for the sim backend.

The reference's entire observability story is a ``debug`` flag gating
prints (e.g. per-send compression ratios,
[ref: p2pnetwork/nodeconnection.py:57-58,79-80]) plus three message
counters [ref: node.py:64-67]. The sockets backend keeps that surface
(``Node.debug``, ``message_count_*``, ``EventLog``); this module is the sim
side (SURVEY.md section 5 "Tracing"): per-round propagation stats as
structured records, and XLA-level profiler capture.

- :func:`run_traced` — run a protocol and emit one JSON line per round
  (round index plus every device-side stat), then a summary line with the
  total wall time. All rounds execute inside one ``lax.scan``, so there is
  no per-round wall clock — stats are computed on device and tracing adds
  one transfer at the end, not one per round.
- :func:`annotate` — name a region so it shows up in profiler timelines
  (``jax.profiler.TraceAnnotation``).
- :func:`profile` — capture an XLA profile (TensorBoard format) around a
  block, via ``jax.profiler.trace``.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import IO, Iterator, Optional, Union

import jax
import numpy as np

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.telemetry import jaxhooks


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name the enclosed device work in profiler timelines."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture an XLA profile of the enclosed block into ``log_dir``
    (view with TensorBoard's profile plugin or Perfetto)."""
    with jax.profiler.trace(log_dir):
        yield


def _open_sink(sink: Union[str, IO, None]):
    if sink is None or hasattr(sink, "write"):
        return sink, False
    return open(sink, "a", encoding="utf-8"), True


def run_traced(
    graph,
    protocol,
    key: jax.Array,
    rounds: int,
    *,
    sink: Union[str, IO, None] = None,
    label: str = "run",
    profile_dir: Optional[str] = None,
):
    """Run ``rounds`` protocol rounds, returning ``(state, records)``.

    ``records`` is a list of dicts, one per round, each holding the round
    index plus every stat the protocol computed on device (floats). When
    ``sink`` is a path or file object, each record is also written as one
    JSON line. ``profile_dir`` additionally captures an XLA profile of the
    compiled run.

    The summary line reports through the telemetry registry (telemetry/):
    ``compile_seconds`` is the backend-compile wall time this run triggered
    (delta of ``jax_compile_seconds_total`` — 0.0 on a cache hit, and when
    jax.monitoring is unavailable), ``device_transfer_bytes`` the size of
    the stats history brought back to host.
    """
    from p2pnetwork_tpu.sim import engine

    reg = telemetry.default_registry()
    hooks_on = jaxhooks.install()  # None-subscription: follows the default
    compile_s0 = jaxhooks.compile_seconds(reg) if hooks_on else 0.0

    ctx = profile(profile_dir) if profile_dir else contextlib.nullcontext()
    t0 = time.perf_counter()
    with ctx:
        with annotate(f"{label}:rounds={rounds}"):
            state, stats = engine.run(graph, protocol, key, rounds)
            jax.block_until_ready(stats)
    wall_s = time.perf_counter() - t0
    compile_s = (jaxhooks.compile_seconds(reg) - compile_s0) if hooks_on \
        else 0.0

    host_stats = {k: np.asarray(v) for k, v in stats.items()}
    transfer_bytes = int(sum(v.nbytes for v in host_stats.values()))
    reg.counter(
        "sim_transfer_bytes_total",
        "Bytes moved by device->host summary transfers.").inc(transfer_bytes)
    records = []
    for i in range(rounds):
        rec = {"label": label, "round": i}
        for k, v in host_stats.items():
            rec[k] = float(v[i])  # graftlint: ignore[host-sync-in-loop] -- host_stats is numpy (single transfer above)
        records.append(rec)
    summary = {
        "label": label,
        "summary": True,
        "rounds": rounds,
        "wall_s": wall_s,
        "compile_seconds": compile_s,
        "device_transfer_bytes": transfer_bytes,
        "n_nodes": graph.n_nodes,
        "n_edges": graph.n_edges,
    }

    f, close = _open_sink(sink)
    if f is not None:
        try:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
            f.write(json.dumps(summary) + "\n")
        finally:
            if close:
                f.close()
    return state, records
