"""Causal broadcast over the sockets backend — vector clocks, batched none.

The reference delivers messages in raw arrival order [ref:
p2pnetwork/nodeconnection.py:207-218 — one callback per frame as the
bytes land]; two broadcasts related by happened-before (B read A's
message, then reacted) can reach a third peer reversed, and every
protocol its users build on ``node_message`` inherits that hazard
silently. The classic repair is Birman–Schiper–Stephenson causal
broadcast: stamp each broadcast with the sender's vector clock, and
hold back any received message until every message it causally depends
on has been delivered. Its transport preconditions — FIFO per-peer
channels, a stable sender id — are exactly what the per-connection TCP
stream and the id handshake already give.

:class:`CausalNode` adds:

- :meth:`send_causal`: broadcast with a vector-clock stamp (runs on the
  node's event loop; safe from any thread);
- :meth:`causal_message`: the delivery hook — invoked in CAUSAL order,
  which is the whole point; also dispatched to the ``callback`` under
  the ``"causal_message"`` event name;
- plain (unstamped) traffic is untouched: it flows through
  ``node_message``'s usual path, so ``CausalNode`` interoperates with
  ordinary peers — causal ordering applies among the peers that speak
  it.

Delivery rule for an envelope from sender ``j`` carrying clock ``W``:
deliver when ``W[j] == vc[j] + 1`` (the next message from ``j``) and
``W[k] <= vc[k]`` for every other ``k`` (all its dependencies are in);
otherwise buffer. Each delivery merges clocks and re-scans the buffer,
so a single arrival can release a whole chain.

Honest limits (the algorithm's, not the implementation's): causal order
is bought with blocking — if a sender crashes after some peers received
its message and others did not, messages causally after it stay
buffered on the peers that missed it (inspect :meth:`undelivered`).
Full resilience needs a reliable-broadcast layer underneath (see
models/bracha.py for the Byzantine-grade version of that idea, on the
sim backend).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

#: Envelope keys. A dict payload carrying both is consumed as a causal
#: envelope and never reaches the plain node_message path.
VC_KEY = "_vc"
VC_FROM_KEY = "_vc_from"


def _le_all(w: Dict[str, int], vc: Dict[str, int], skip: str) -> bool:
    return all(c <= vc.get(k, 0) for k, c in w.items() if k != skip)


class CausalNode(Node):
    """A :class:`Node` whose stamped broadcasts are delivered causally."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Both mutated only on the event loop (send_causal posts there).
        self.vc: Dict[str, int] = {}
        self._held: List[Tuple[str, Dict[str, int], Any, NodeConnection]] = []

    # ------------------------------------------------------------ app API

    def send_causal(self, data, compression: str = "none") -> None:
        """Broadcast ``data`` to every peer with a causal stamp.

        Thread-safe: the clock tick and the sends run as one event-loop
        callback, so concurrent callers serialize and every stamp is
        unique and ordered."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")

        def _do():
            self.vc[self.id] = self.vc.get(self.id, 0) + 1
            envelope = {VC_KEY: dict(self.vc), VC_FROM_KEY: self.id,
                        "payload": data}
            self.send_to_nodes(envelope, compression=compression)
            # Standard self-delivery: the sender sees its own message in
            # the causal stream too (node=None marks an own message).
            self.causal_message(None, data)

        loop.call_soon_threadsafe(_do)

    def causal_message(self, node: NodeConnection, data) -> None:
        """A causally-ordered delivery. Override me. ``node`` is the
        connection the envelope arrived on (None for this node's own
        broadcasts, self-delivered at send time); the ORIGINATOR id is
        in the clock you just merged."""
        self.debug_print(f"causal_message: {data!r}")
        self._dispatch("causal_message", node, data)

    def undelivered(self) -> int:
        """Envelopes held back waiting on causal dependencies — nonzero
        steady-state means a dependency was lost (crashed sender)."""
        return len(self._held)

    # ---------------------------------------------------------- delivery

    def _deliverable(self, sender: str, w: Dict[str, int]) -> bool:
        return (w.get(sender, 0) == self.vc.get(sender, 0) + 1
                and _le_all(w, self.vc, skip=sender))

    def _deliver(self, sender: str, w: Dict[str, int], payload,
                 conn: NodeConnection) -> None:
        for k, c in w.items():
            if c > self.vc.get(k, 0):
                self.vc[k] = c
        self.causal_message(conn, payload)

    def _on_envelope(self, conn: NodeConnection, envelope: dict) -> None:
        sender = envelope[VC_FROM_KEY]
        w = envelope[VC_KEY]
        payload = envelope.get("payload")
        if w.get(sender, 0) <= self.vc.get(sender, 0):
            return  # stale duplicate (already delivered); FIFO TCP makes
            #         this reachable only via app-level resend
        if not self._deliverable(sender, w):
            self._held.append((sender, w, payload, conn))
            return
        self._deliver(sender, w, payload, conn)
        # One delivery can release a chain: re-scan until a full pass
        # holds nothing deliverable. The re-scan also PURGES entries gone
        # stale since they were buffered — a resent copy of a message that
        # was held at arrival passes the arrival staleness check, and once
        # the original delivers it would otherwise sit in _held forever
        # (inflating undelivered() and leaking under repeated resends).
        progress = True
        while progress and self._held:
            progress = False
            for i, (s, hw, hp, hc) in enumerate(self._held):
                if hw.get(s, 0) <= self.vc.get(s, 0):
                    del self._held[i]
                    progress = True
                    break
                if self._deliverable(s, hw):
                    del self._held[i]
                    self._deliver(s, hw, hp, hc)
                    progress = True
                    break

    # ------------------------------------------------------ interception

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict) and VC_KEY in data and VC_FROM_KEY in data:
            self._on_envelope(node, data)
            return
        super().node_message(node, data)
