"""Node orchestration for the sockets backend.

``Node`` is the same concept as the reference's ``Node``
[ref: p2pnetwork/node.py:13]: a TCP server plus peer registry plus
broadcast/unicast sender, extended by subclassing its event methods or by
passing a ``callback(event, main_node, connected_node, data)``
[ref: node.py:24-29]. The full ten-event vocabulary, the
``create_new_connection`` factory seam [ref: node.py:196-201] and the
reconnect policy hook [ref: node.py:354-363] are preserved name-for-name, and
the wire format interoperates with live reference nodes (see wire.py).

Runtime design (deliberately different, SURVEY.md section 7): instead of one
accept thread per node plus one thread per connection with 10 ms poll loops
[ref: node.py:227-280, nodeconnection.py:186-229], each ``Node`` runs a single
asyncio event loop on one background thread. All peer-registry state is
mutated only from that loop, which designs out the reference's unlocked
cross-thread list mutation (SURVEY.md section 2.3.6). Public methods are
thread-safe facades that post onto the loop.

Deliberate fixes over the reference (SURVEY.md section 2.3), each noted
inline: single reconnect key (2.3.1), no mutable default argument (2.3.5),
``message_count_rerr`` actually counts errors (2.3.7), EOF during the
outbound handshake is an error instead of a phantom empty-id peer.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import random
import socket
import threading
import time
from typing import Callable, List, Optional, Union

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.config import NodeConfig
from p2pnetwork_tpu.nodeconnection import NodeConnection
from p2pnetwork_tpu.utils import EventLog, generate_id


class Node(threading.Thread):
    """A peer node: TCP server, peer registry, broadcast, event hooks.

    Constructor parity [ref: node.py:32]: ``Node(host, port, id=None,
    callback=None, max_connections=0)``; ``config`` adds typed tunables the
    reference hard-codes (SURVEY.md section 5 "Config"). Binding happens here,
    so port conflicts surface at construction like the reference's
    ``init_server`` [ref: node.py:92-98]. ``port=0`` binds an ephemeral port
    and stores the chosen one on ``self.port``.

    ``Node`` IS a ``threading.Thread``, like the reference's
    [ref: node.py:13] — ``isinstance`` checks, ``.name``, ``.daemon`` and
    ``join``/``is_alive`` behave as applications expect. The thread body
    (:meth:`run`) hosts the asyncio event loop rather than a blocking
    accept loop.
    """

    def __init__(self, host: str, port: int, id: Optional[str] = None,
                 callback: Optional[Callable] = None, max_connections: int = 0,
                 config: Optional[NodeConfig] = None,
                 registry: Optional[telemetry.Registry] = None):
        super().__init__(name=f"Node({host}:{port})", daemon=True)
        self.host = host
        self.port = port
        self.callback = callback
        self.config = config or NodeConfig()

        # Set when the node should stop [ref: node.py:36]. Constructed
        # through the concurrency seam (like every primitive in this
        # plane) so graftrace can instrument it.
        self.terminate_flag = concurrency.event()

        # Peer registries [ref: node.py:46-52]. Only mutated on the loop.
        self.nodes_inbound: List[NodeConnection] = []
        self.nodes_outbound: List[NodeConnection] = []
        self.reconnect_to_nodes: List[dict] = []

        # Identity [ref: node.py:54-58].
        self.id = generate_id(host, port) if id is None else str(id)

        # Message counters [ref: node.py:64-67]; rerr is live here (2.3.7).
        self.message_count_send = 0
        self.message_count_recv = 0
        self.message_count_rerr = 0

        self.max_connections = max_connections  # [ref: node.py:70]
        self.debug = False  # [ref: node.py:73]

        # Structured event history (addition; SURVEY.md section 5 "Metrics").
        self.event_log = EventLog()

        # Telemetry plane (telemetry/): same registry across every node in
        # the process unless one is injected per node. The legacy
        # message_count_* ints stay authoritative for parity; _record_*
        # below keeps them and these families in lockstep.
        self.telemetry = registry if registry is not None \
            else telemetry.default_registry()
        t = self.telemetry
        self._m_sent = t.counter(
            "p2p_messages_sent_total", "Messages queued for send, per node.",
            ("node",)).labels(self.id)
        self._m_recv = t.counter(
            "p2p_messages_received_total",
            "Frames received and delivered upward, per node.",
            ("node",)).labels(self.id)
        self._m_rerr = t.counter(
            "p2p_recv_errors_total",
            "Send/receive/parse errors (the reference's message_count_rerr, "
            "live here).", ("node",)).labels(self.id)
        self._m_bytes_sent = t.counter(
            "p2p_bytes_sent_total", "Framed bytes written, per peer.",
            ("node", "peer"))
        self._m_bytes_recv = t.counter(
            "p2p_bytes_received_total", "Raw bytes read, per peer.",
            ("node", "peer"))
        self._m_handle = t.histogram(
            "p2p_message_handle_seconds",
            "Per-message latency from frame decode through the "
            "node_message handler.", ("node",)).labels(self.id)
        self._m_conns = t.gauge(
            "p2p_connections", "Currently connected peers, by direction.",
            ("node", "direction"))
        self._m_reconnects = t.counter(
            "p2p_reconnect_attempts_total",
            "Reconnect attempts against registered dropped peers.",
            ("node",)).labels(self.id)
        self._m_next_retry = t.gauge(
            "p2p_reconnect_next_retry_seconds",
            "Seconds until the next reconnect attempt of a registered "
            "dropped peer (0 while connected).", ("node", "peer"))
        self._m_reconnect_trigger_timeouts = t.counter(
            "p2p_reconnect_trigger_timeouts_total",
            "Manual reconnect_nodes() triggers that timed out waiting on a "
            "busy or wedged event loop.", ("node",)).labels(self.id)
        self._m_undelivered = t.counter(
            "p2p_shutdown_undelivered_total",
            "Bytes still queued toward peers when a deadline-bounded "
            "Node.stop(deadline=) gave up draining them.",
            ("node",)).labels(self.id)
        # Decorrelated-jitter draws for the reconnect backoff; per-node so
        # chaos tests can reseed one node without touching global state.
        self._reconnect_rng = random.Random()
        self._m_events = t.counter(
            "p2p_events_total", "Framework events fired, by event name.",
            ("node", "event"))

        # Bind now so errors surface in the constructor [ref: node.py:92-98].
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((self.host, self.port))
        self.sock.listen(self.config.listen_backlog)
        self.sock.setblocking(False)
        if self.port == 0:
            self.port = self.sock.getsockname()[1]
            # Re-stamp the thread name with the resolved ephemeral port so
            # thread dumps distinguish concurrent port-0 nodes.
            self.name = f"Node({self.host}:{self.port})"
        print(f"Initialisation of the Node on port: {self.port} on node ({self.id})")

        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._stop_event: Optional[asyncio.Event] = None
        # Drain budget of a deadline-bounded stop(); None = legacy close.
        self._stop_deadline: Optional[float] = None
        # NOT named _started: threading.Thread owns that attribute.
        self._ready = concurrency.event()

    # ------------------------------------------------------------ telemetry

    def _record_send(self) -> None:
        """Bump the send counter — legacy int and telemetry family together."""
        self.message_count_send += 1
        self._m_sent.inc()

    def _record_recv(self) -> None:
        self.message_count_recv += 1
        self._m_recv.inc()

    def _record_rerr(self) -> None:
        self.message_count_rerr += 1
        self._m_rerr.inc()

    def _update_conn_gauges(self) -> None:
        self._m_conns.labels(self.id, "inbound").set(len(self.nodes_inbound))
        self._m_conns.labels(self.id, "outbound").set(len(self.nodes_outbound))

    # ------------------------------------------------------------- registry

    @property
    def all_nodes(self) -> List[NodeConnection]:
        """All connected peers, inbound then outbound [ref: node.py:75-78]."""
        return self.nodes_inbound + self.nodes_outbound

    def debug_print(self, message: str) -> None:
        """Print ``message`` when ``self.debug`` is set [ref: node.py:80-83]."""
        if self.debug:
            print(f"DEBUG ({self.id}): {message}")

    def generate_id(self) -> str:
        """Generate a fresh unique id [ref: node.py:85-90]."""
        return generate_id(self.host, self.port)

    def print_connections(self) -> None:
        """Print an inbound/outbound connection overview [ref: node.py:100-104]."""
        print("Node connection overview:")
        print(f"Total nodes connected with us: {len(self.nodes_inbound)}")
        print(f"Total nodes connected to     : {len(self.nodes_outbound)}")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Start the node's thread and begin accepting peers
        [ref: node.py:13 — ``Node`` is a ``threading.Thread``].

        Unlike a bare ``Thread.start``, returns only once the server is
        accepting (or failed to start), so ``connect_with_node`` right
        after ``start()`` never races the loop coming up. The wait is
        BOUNDED: a loop that cannot come up within 30 s (interpreter
        wedged before ``_main`` runs its first statement) raises instead
        of hanging the caller forever."""
        super().start()
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError(
                "Node.start: event loop did not come up within 30s")

    def run(self) -> None:
        """Thread body: host the node's asyncio event loop."""
        asyncio.run(self._main())

    async def _main(self) -> None:
        """Loop body: serve, tick the reconnect registry, shut down cleanly.

        The asyncio analog of the reference's accept loop + epilogue
        [ref: node.py:227-280]."""
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        try:
            self._server = await asyncio.start_server(self._handle_inbound, sock=self.sock)
        except Exception as e:
            self.debug_print(f"Node: could not start server: {e}")
            self._ready.set()
            return
        self._ready.set()
        try:
            while not self._stop_event.is_set():
                try:
                    await asyncio.wait_for(
                        self._stop_event.wait(), timeout=self.config.reconnect_interval
                    )
                except asyncio.TimeoutError:
                    # Periodic reconnect check; the reference runs this every
                    # accept-loop tick [ref: node.py:265].
                    await self._reconnect_tick()
        finally:
            await self._shutdown()

    async def _shutdown(self) -> None:
        """Stop epilogue [ref: node.py:269-280]: close server, stop peers, join.

        A deadline-bounded stop first drains outbound write buffers within
        the deadline (:meth:`stop`); whatever is still queued past it is
        counted into ``p2p_shutdown_undelivered_total`` and force-aborted,
        so the supervised-shutdown story holds on the sockets backend too:
        bounded exit, with the loss measured instead of silent."""
        print("Node stopping...")
        if self._server is not None:
            self._server.close()
        conns = list(self.all_nodes)
        if self._stop_deadline is not None:
            await self._drain_outbound(conns, self._stop_deadline)
        for conn in conns:
            conn.stop()
        for conn in conns:
            await conn.wait_closed()
        if self._server is not None:
            # Python 3.12+: wait_closed() also waits for the connection
            # transports start_server spawned, so it must come after the
            # per-connection closes above or it deadlocks.
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5.0)
            except asyncio.TimeoutError:
                self.debug_print("Node: server.wait_closed timed out")
        print("Node stopped")

    async def _drain_outbound(self, conns, deadline: float) -> int:
        """Wait (up to ``deadline`` seconds) for every peer's write buffer
        to empty; returns the bytes abandoned past the deadline.

        Undrained connections are marked for force-abort so the close
        epilogue stays prompt — a peer that stopped reading must not turn
        a bounded stop into a 10 s-per-connection graceful-close wait.
        Abandoned bytes count into ``p2p_shutdown_undelivered_total``."""
        def _buffered(conn) -> int:
            transport = conn.writer.transport
            if transport is None or transport.is_closing():
                return 0
            try:
                return int(transport.get_write_buffer_size())
            except Exception:
                return 0

        give_up_at = time.monotonic() + max(float(deadline), 0.0)
        while True:
            remaining = sum(_buffered(c) for c in conns)
            if remaining == 0:
                return 0
            if time.monotonic() >= give_up_at:
                break
            await asyncio.sleep(0.01)
        for conn in conns:
            if _buffered(conn) > 0:
                conn._abort = True  # undrained: stop() force-aborts
        self._m_undelivered.inc(remaining)
        self.event_log.record(
            "shutdown_undelivered", None,
            {"bytes": remaining, "deadline": deadline})
        self.debug_print(
            f"stop: abandoned {remaining} undelivered byte(s) after "
            f"{deadline}s drain deadline")
        return remaining

    def stop(self, deadline: Optional[float] = None) -> None:
        """Request the node to stop [ref: node.py:191-194].

        Thread-safe and idempotent, like the reference's flag-set.

        ``deadline`` (seconds) opts into a *measured* shutdown: the stop
        epilogue drains every peer's outbound queue for at most that long
        before closing; bytes still queued past the deadline are reported
        via the ``p2p_shutdown_undelivered_total`` counter and a
        ``shutdown_undelivered`` event-log record, and their connections
        are force-aborted so the stop itself stays bounded. Without a
        deadline the legacy behavior is unchanged (graceful close, the
        per-connection ``wait_closed`` 10 s bound)."""
        self.node_request_to_stop()
        if deadline is not None:
            self._stop_deadline = float(deadline)
        self.terminate_flag.set()
        loop, stop_event = self._loop, self._stop_event
        if loop is not None and stop_event is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(stop_event.set)
            except RuntimeError:
                pass  # loop already closed — nothing left to stop

    # join() and is_alive() are the inherited threading.Thread methods.

    # ------------------------------------------------------------- inbound

    async def _handle_inbound(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        """Accept-path: gate on max_connections, handshake, register, event.

        Mirrors [ref: node.py:232-263]: receive the peer's ``"id:port"``
        first, then send our id; the stored port is the peer's *server* port
        when present (inbound port semantics, SURVEY.md section 2.3.8)."""
        peername = writer.get_extra_info("peername") or ("?", 0)
        try:
            self.debug_print("Node: Wait for incoming connection")
            # Connection-limit gate [ref: node.py:239]; 0 means unlimited.
            if self.max_connections != 0 and len(self.nodes_inbound) >= self.max_connections:
                self.debug_print(
                    "New connection is closed. You have reached the maximum connection limit!"
                )
                writer.close()
                return
            handshake = await asyncio.wait_for(
                reader.read(4096), timeout=self.config.connect_timeout
            )
            connected_node_id = handshake.decode("utf-8")
            connected_node_port = peername[1]  # backward compat [ref: node.py:242]
            if ":" in connected_node_id:
                connected_node_id, port_str = connected_node_id.split(":")
                connected_node_port = int(port_str)
            writer.write(self.id.encode("utf-8"))  # [ref: node.py:246]
            await writer.drain()

            conn = self.create_new_connection(
                (reader, writer), connected_node_id, peername[0], connected_node_port
            )
            conn.start()
            self.nodes_inbound.append(conn)
            self._update_conn_gauges()
            self.inbound_node_connected(conn)
        except Exception as e:
            self._record_rerr()
            try:
                writer.close()
            except Exception:
                pass
            self.inbound_node_connection_error(e)

    # ------------------------------------------------------------- outbound

    def connect_with_node(self, host: str, port: int, reconnect: bool = False) -> bool:
        """Connect to a peer at ``host:port`` [ref: node.py:122-176].

        Guard parity: self-connect refused (``False``), already-connected
        host:port is a no-op (``True``), duplicate peer id after handshake
        sends the reference's ``"CLOSING: ..."`` string and reports ``True``.
        With ``reconnect=True`` the address is registered for automatic
        reconnection [ref: node.py:165-169].

        Thread-safe. When called from within an event handler (i.e. on the
        node's own loop), the connection attempt is scheduled in the
        background and this returns ``True`` if the guards pass; failures are
        then reported through ``outbound_node_connection_error`` — the
        reference's error channel [ref: node.py:173-176]. Use
        :meth:`connect_with_node_async` in async code for the exact result.
        """
        if host == self.host and port == self.port:
            print("connect_with_node: Cannot connect with yourself!!")
            return False
        for node in self.all_nodes:
            if node.host == host and node.port == port:
                print(f"connect_with_node: Already connected with this node ({node.id}).")
                return True
        loop = self._loop
        if loop is None or not loop.is_running():
            self.debug_print("connect_with_node: node is not running — call start() first")
            return False
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            loop.create_task(self.connect_with_node_async(host, port, reconnect))
            return True
        fut = asyncio.run_coroutine_threadsafe(
            self.connect_with_node_async(host, port, reconnect), loop
        )
        # Bounded like reconnect_nodes(): a healthy attempt legitimately
        # spends one connect timeout on TCP establishment and one on the
        # handshake read; an unbounded .result() would hang this caller
        # forever on a wedged loop (e.g. a stuck user handler).
        bound = 2.0 * self.config.connect_timeout + 1.0
        try:
            return fut.result(timeout=bound)
        except concurrent.futures.TimeoutError:
            self.event_log.record(
                "connect_trigger_timeout", None,
                {"host": host, "port": port, "timeout": bound})
            self.debug_print(
                f"connect_with_node: no result within {bound}s — event "
                "loop busy or wedged; the attempt continues in the "
                "background (outbound_node_connected/. .._error still fire)"
            )
            return False

    async def connect_with_node_async(self, host: str, port: int,
                                      reconnect: bool = False) -> bool:
        """Async core of :meth:`connect_with_node`; runs on the node's loop."""
        if host == self.host and port == self.port:
            print("connect_with_node: Cannot connect with yourself!!")
            return False
        for node in self.all_nodes:
            if node.host == host and node.port == port:
                print(f"connect_with_node: Already connected with this node ({node.id}).")
                return True
        node_ids = [node.id for node in self.all_nodes]
        writer = None
        try:
            self.debug_print(f"connecting to {host} port {port}")
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), timeout=self.config.connect_timeout
            )
            # Plaintext id handshake, parity for interop [ref: node.py:148-150]:
            # send "id:port", receive the peer's id.
            writer.write(f"{self.id}:{self.port}".encode("utf-8"))
            await writer.drain()
            handshake = await asyncio.wait_for(
                reader.read(4096), timeout=self.config.connect_timeout
            )
            if not handshake:
                # Peer closed before completing the handshake (e.g. its
                # connection limit). The reference would register a phantom
                # empty-id peer here; we fail instead (deliberate fix).
                raise ConnectionError("peer closed the connection during the handshake")
            connected_node_id = handshake.decode("utf-8")

            # Duplicate-peer guard [ref: node.py:153-156].
            if self.id == connected_node_id or connected_node_id in node_ids:
                writer.write("CLOSING: Already having a connection together".encode("utf-8"))
                writer.close()
                return True

            conn = self.create_new_connection((reader, writer), connected_node_id, host, port)
            conn.start()
            self.nodes_outbound.append(conn)
            self._update_conn_gauges()
            self.outbound_node_connected(conn)

            # Reconnect registration [ref: node.py:165-169]; single "trials"
            # key — the reference writes "tries" but reads "trials"
            # (SURVEY.md section 2.3.1).
            if reconnect:
                self.debug_print(
                    f"connect_with_node: Reconnection check is enabled on node {host}:{port}"
                )
                self.reconnect_to_nodes.append({
                    "host": host, "port": port, "trials": 0,
                    # Per-entry backoff state: last drawn delay and the
                    # monotonic deadline of the next attempt.
                    "backoff": 0.0, "next_retry_at": 0.0,
                })
            return True
        except Exception as error:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass
            self._record_rerr()
            self.debug_print(f"connect_with_node: Could not connect with node. ({error})")
            self.outbound_node_connection_error(error)
            return False

    def disconnect_with_node(self, node: NodeConnection) -> None:
        """Close one outbound connection [ref: node.py:178-189].

        Fires ``node_disconnect_with_outbound_node`` before closing; peers we
        did not initiate the connection to cannot be disconnected this way."""
        if node in self.nodes_outbound:
            self.node_disconnect_with_outbound_node(node)
            node.stop()
        else:
            self.debug_print(
                "Node disconnect_with_node: cannot disconnect with a node with which "
                "we are not connected."
            )

    # ------------------------------------------------------------ messaging

    def send_to_nodes(self, data: Union[str, dict, bytes],
                      exclude: Optional[List[NodeConnection]] = None,
                      compression: str = "none") -> None:
        """Broadcast ``data`` to every connected peer not in ``exclude``.

        [ref: node.py:106-112]; ``exclude`` defaults to ``None`` instead of a
        shared mutable list (SURVEY.md section 2.3.5)."""
        exclude = exclude or []
        for n in self.all_nodes:
            if n not in exclude:
                self.send_to_node(n, data, compression)

    def send_to_node(self, n: NodeConnection, data: Union[str, dict, bytes],
                     compression: str = "none") -> None:
        """Unicast ``data`` to peer ``n`` [ref: node.py:114-120].

        Counter-then-membership-check order preserved [ref: node.py:116-117]."""
        self._record_send()
        if n in self.all_nodes:
            n.send(data, compression=compression)
        else:
            self.debug_print("Node send_to_node: Could not send the data, node is not found!")

    # ------------------------------------------------------------ factories

    def create_new_connection(self, connection, id: str, host: str, port: int) -> NodeConnection:
        """Factory seam for substituting a custom connection class
        [ref: node.py:196-201]. ``connection`` is an asyncio
        ``(StreamReader, StreamWriter)`` pair."""
        return NodeConnection(self, connection, id, host, port)

    # ------------------------------------------------------------ reconnect

    async def _reconnect_tick(self) -> None:
        """Re-establish registered outbound connections that dropped.

        [ref: node.py:203-225] with the single-key fix (SURVEY.md 2.3.1): each
        entry is ``{"host", "port", "trials", "backoff", "next_retry_at"}``;
        the policy hook ``node_reconnection_error`` decides retry (True) vs
        deregister (False) per trial count.

        Retry cadence is per-entry exponential backoff with decorrelated
        jitter (delay_{n+1} ~ U[base, 3 * delay_n], capped at
        ``reconnect_backoff_max``) instead of the reference's fixed-interval
        hammering of dead peers; ``reconnect_interval`` stays the tick floor.
        Backoff resets on successful reconnect; the time to the next attempt
        is published as the ``p2p_reconnect_next_retry_seconds`` gauge.

        Due entries dial CONCURRENTLY: a serial walk would stall the tick
        (and node shutdown, and manual triggers) for up to
        ``K * connect_timeout`` when K peers are unreachable rather than
        refusing. Each entry's next-retry deadline is stamped AFTER its
        dial completes, from a fresh clock read — computing it up front
        would let a slow dial consume the whole delay before it starts."""
        dials = []
        for entry in list(self.reconnect_to_nodes):
            host, port = entry["host"], entry["port"]
            peer_key = f"{host}:{port}"
            self.debug_print(f"reconnect_nodes: Checking node {host}:{port}")
            found = any(
                n.host == host and n.port == port for n in self.nodes_outbound
            )
            if found:
                entry["trials"] = 0
                entry["backoff"] = 0.0
                entry["next_retry_at"] = 0.0
                self._m_next_retry.labels(self.id, peer_key).set(0.0)
                self.debug_print(f"reconnect_nodes: Node {host}:{port} still running!")
                continue
            now = time.monotonic()
            next_retry_at = entry.get("next_retry_at", 0.0)
            if now < next_retry_at:
                self._m_next_retry.labels(self.id, peer_key).set(next_retry_at - now)
                continue
            if entry.get("dialing"):
                # A dial from an overlapping tick (manual trigger racing
                # the periodic one) is still in flight; a second dial
                # would double-count trials and can register a duplicate
                # connection if the peer comes back mid-window.
                continue
            entry["trials"] += 1
            self._m_reconnects.inc()
            if self.node_reconnection_error(host, port, entry["trials"]):
                entry["dialing"] = True
                dials.append(self._dial_registered(entry, host, port))
            else:
                self.debug_print(
                    f"reconnect_nodes: Removing node ({host}:{port}) from the reconnection list!"
                )
                self.reconnect_to_nodes.remove(entry)
                # Deregistered: prune the gauge so the dead peer does not
                # leave a forever-sample behind.
                self._m_next_retry.remove(self.id, peer_key)
        if dials:
            await asyncio.gather(*dials)

    async def _dial_registered(self, entry: dict, host: str, port: int) -> None:
        """One reconnect dial plus its post-dial backoff bookkeeping."""
        try:
            await self.connect_with_node_async(host, port)
        finally:
            entry["dialing"] = False
            base = self.config.reconnect_backoff_base
            prev = entry.get("backoff") or base
            backoff = min(self.config.reconnect_backoff_max,
                          self._reconnect_rng.uniform(base, prev * 3.0))
            entry["backoff"] = backoff
            entry["next_retry_at"] = time.monotonic() + backoff
            # A successful dial is reset by the found-check on the next tick.
            self._m_next_retry.labels(self.id, f"{host}:{port}").set(backoff)

    def reconnect_nodes(self) -> None:
        """Manual trigger of one reconnect check [ref: node.py:203].

        Thread-safe; from an event handler (i.e. on the node's own loop) the
        check is scheduled in the background instead of awaited, since
        blocking the loop on its own work would deadlock.

        The cross-thread wait is BOUNDED at ``2 * config.connect_timeout``
        plus one second of headroom — a healthy tick's slowest dial may
        legitimately consume one connect timeout on TCP establishment and a
        second on the handshake read: an unbounded ``.result()`` would hang
        the caller forever if the loop is wedged (e.g. a stuck user handler).
        On timeout the check keeps running on the loop, and the caller gets
        a structured warning — a ``reconnect_trigger_timeout`` event-log
        record plus the ``p2p_reconnect_trigger_timeouts_total`` counter."""
        loop = self._loop
        if loop is None or not loop.is_running():
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            loop.create_task(self._reconnect_tick())
            return
        fut = asyncio.run_coroutine_threadsafe(self._reconnect_tick(), loop)
        bound = 2.0 * self.config.connect_timeout + 1.0
        try:
            fut.result(timeout=bound)
        except concurrent.futures.TimeoutError:
            self._m_reconnect_trigger_timeouts.inc()
            self.event_log.record(
                "reconnect_trigger_timeout", None, {"timeout": bound})
            self.debug_print(
                f"reconnect_nodes: tick did not complete within {bound}s — "
                "event loop busy or wedged; the check continues in the "
                "background"
            )

    # -------------------------------------------------------------- events
    #
    # The ten-event Extension API [ref: node.py:282-363]: subclasses override
    # these; each also dispatches to the optional callback with the exact
    # event-name strings of the reference, and records into the event log.

    def _dispatch(self, event: str, connected_node, data) -> None:
        peer_id = getattr(connected_node, "id", None)
        self.event_log.record(event, peer_id, data)
        self._m_events.labels(self.id, event).inc()
        if self.callback is not None:
            self.callback(event, self, connected_node, data)

    def outbound_node_connected(self, node: NodeConnection) -> None:
        """We successfully connected to ``node`` [ref: node.py:282-287]."""
        self.debug_print(f"outbound_node_connected: {node.id}")
        self._dispatch("outbound_node_connected", node, {})

    def outbound_node_connection_error(self, exception: Exception) -> None:
        """An outbound connection attempt failed [ref: node.py:289-293]."""
        self.debug_print(f"outbound_node_connection_error: {exception}")
        self._dispatch("outbound_node_connection_error", None, {"exception": exception})

    def inbound_node_connected(self, node: NodeConnection) -> None:
        """A peer connected to us [ref: node.py:295-299]."""
        self.debug_print(f"inbound_node_connected: {node.id}")
        self._dispatch("inbound_node_connected", node, {})

    def inbound_node_connection_error(self, exception: Exception) -> None:
        """Accepting a peer failed [ref: node.py:301-305]."""
        self.debug_print(f"inbound_node_connection_error: {exception}")
        self._dispatch("inbound_node_connection_error", None, {"exception": exception})

    def node_disconnected(self, node: NodeConnection) -> None:
        """Route a dead connection to the inbound/outbound variant
        [ref: node.py:307-319], removing it from the registry."""
        self.debug_print(f"node_disconnected: {node.id}")
        if node in self.nodes_inbound:
            self.nodes_inbound.remove(node)
            self._update_conn_gauges()
            self.inbound_node_disconnected(node)
        if node in self.nodes_outbound:
            self.nodes_outbound.remove(node)
            self._update_conn_gauges()
            self.outbound_node_disconnected(node)

    def inbound_node_disconnected(self, node: NodeConnection) -> None:
        """A peer that had connected to us went away [ref: node.py:321-326]."""
        self.debug_print(f"inbound_node_disconnected: {node.id}")
        self._dispatch("inbound_node_disconnected", node, {})

    def outbound_node_disconnected(self, node: NodeConnection) -> None:
        """A peer we had connected to went away [ref: node.py:328-332]."""
        self.debug_print(f"outbound_node_disconnected: {node.id}")
        self._dispatch("outbound_node_disconnected", node, {})

    def node_message(self, node: NodeConnection, data) -> None:
        """A peer sent us a message [ref: node.py:334-338]."""
        self.debug_print(f"node_message: {node.id}: {data}")
        self._dispatch("node_message", node, data)

    def node_disconnect_with_outbound_node(self, node: NodeConnection) -> None:
        """We are about to close an outbound connection [ref: node.py:340-345]."""
        self.debug_print(f"node wants to disconnect with other outbound node: {node.id}")
        self._dispatch("node_disconnect_with_outbound_node", node, {})

    def node_request_to_stop(self) -> None:
        """The node was asked to stop [ref: node.py:347-352].

        Callback signature parity: the reference passes ``{}`` for the
        connected-node argument here [ref: node.py:352]."""
        self.debug_print("node is requested to stop!")
        self.event_log.record("node_request_to_stop", None, {})
        self._m_events.labels(self.id, "node_request_to_stop").inc()
        if self.callback is not None:
            self.callback("node_request_to_stop", self, {}, {})

    def node_reconnection_error(self, host: str, port: int, trials: int) -> bool:
        """Reconnect policy hook [ref: node.py:354-363]: return ``True`` to
        keep retrying ``host:port``, ``False`` to deregister it."""
        self.debug_print(
            f"node_reconnection_error: Reconnecting to node {host}:{port} (trials: {trials})"
        )
        return True

    # ------------------------------------------------------------------ repr

    def __str__(self) -> str:
        return f"Node: {self.host}:{self.port}"

    def __repr__(self) -> str:
        return f"<Node {self.host}:{self.port} id: {self.id}>"
