"""Auto-sharded (GSPMD) protocol execution: annotate shardings, let XLA
insert the collectives.

The explicit ring path (parallel/sharded.py) hand-places every ``ppermute``;
this module is the complementary idiom from the JAX sharding playbook: put
the graph's arrays on the mesh with named shardings and run the *unchanged*
single-device engine — the compiler partitions the computation and inserts
all-gathers/reduce-scatters where edges cross shards. Any protocol written
against the engine (Flood, Gossip, SIR, user protocols) scales this way
with zero protocol changes; the explicit ring remains the
bandwidth-predictable path for the flood benchmark.

Layouts: every per-node array is sharded on its leading (node) axis, every
per-edge array on its edge axis, the neighbor table on rows. The blocked
and hybrid representations carry over too — buckets are destination-block
(node-order) slabs, so their leading axis shards in alignment with the
node axis. Use ``method="hybrid-blocked"`` here: the diagonal rolls and
the one-hot einsum remainder are all partitionable ops, which closes most
of the gap to the explicit ring path (the plain segment lowering pays the
full scatter floor); the Pallas remainder kernel (``method="hybrid"``)
stays single-chip — a pallas_call is an opaque custom call the
partitioner would have to replicate.

Communication evidence (tests/test_auto_comm.py inspects the compiled
HLO): for segment-method Flood/SIR on an 8-device mesh, every collective
GSPMD inserts is node-extent — the bool frontier (N bytes) for flood, the
f32 pressure signal (4N bytes) for SIR, plus scalar stats all-reduces —
and edge-extent arrays are never moved. That is the bandwidth-sane
partitioning (per-round cross-shard volume on the order of the node
state, like the explicit ring path, delivered as compiler-placed
collectives instead of S ppermute hops). The tests bound every
collective's payload to node extent — including variadic combined and
async forms — so a compiler or layout change that regresses to
edge-extent traffic fails loudly.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pnetwork_tpu.parallel.mesh import DEFAULT_AXIS
from p2pnetwork_tpu.sim.graph import Graph

#: The explicit ring's halo-exchange backends. resolve_comm validates
#: against parallel/sharded.COMM_BACKENDS itself (lazy import — sharded
#: pulls in jax); this literal only serves the docstring/error text and
#: is pinned equal to sharded's by tests/test_ring.py.
COMM_BACKENDS = ("ppermute", "pallas")


def resolve_comm(comm: str = "auto") -> str:
    """Route the ring path's halo-exchange backend (``comm=`` knob on every
    parallel/sharded.py entry point, ``MeshConfig.comm`` in config.py).

    - ``"ppermute"``: XLA collective-permute — the portable default; the
      compiler's latency-hiding scheduler may overlap it with the bucket
      compute the ring bodies issue after it.
    - ``"pallas"``: ``pltpu.make_async_remote_copy`` ring-DMA kernels
      (ops/pallas_ring.py). On the MXU bucket layout the hop is FUSED
      under the blocked segment sum (genuine in-kernel overlap); on the
      segment layouts today's hop kernel is start+wait in one call —
      measure before preferring it there (sharded._RingComm's overlap
      note). Native on TPU; on CPU it runs the Pallas interpreter
      (orders of magnitude slower — kept for the bit-identity parity
      CI, tests/test_ring.py).
    - ``"auto"``: pallas on a TPU backend, ppermute elsewhere — the same
      shape of routing ``ops/segment.py`` does for kernel methods.
    """
    if comm == "auto":
        import jax

        return "pallas" if jax.default_backend() == "tpu" else "ppermute"
    from p2pnetwork_tpu.parallel.sharded import COMM_BACKENDS as _BACKENDS

    if comm not in _BACKENDS:
        raise ValueError(
            f"comm must be one of {_BACKENDS + ('auto',)}, got {comm!r}")
    return comm


def shard_graph_auto(graph: Graph, mesh: Mesh,
                     axis_name: str = DEFAULT_AXIS) -> Graph:
    """Return ``graph`` with its arrays placed on ``mesh``, node/edge axes
    sharded. Shapes are already padded to multiples of 128, so any mesh of
    up to 128 devices divides them evenly."""
    # The compiler-inserted-collectives idiom needs Auto axes: under JAX's
    # explicit sharding-in-types (the make_mesh default), a node-sharded
    # gather by edge-sharded indices is a type error instead of an
    # auto-partitioned program.
    try:
        mesh = Mesh(
            mesh.devices, mesh.axis_names,
            axis_types=(jax.sharding.AxisType.Auto,) * len(mesh.axis_names),
        )
    except (AttributeError, TypeError):
        pass  # jax 0.4.x (this image): every mesh axis is Auto already
    spec = NamedSharding(mesh, P(axis_name))

    def put(x):
        return None if x is None else jax.device_put(x, spec)

    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def put_blocked(blocked):
        # BlockedEdges buckets are destination blocks in node order, so
        # sharding their leading axis aligns each bucket with the shard
        # that owns its destination nodes; the einsum stays local and only
        # the (node-extent) signal gather crosses shards. A remainder with
        # fewer buckets than shards (tiny graphs) is replicated instead —
        # device_put needs even division, and at that size it is noise.
        if blocked is None:
            return None
        div = blocked.src.shape[0] % axis_size == 0
        bspec = NamedSharding(mesh, P(axis_name) if div else P())
        return dataclasses.replace(
            blocked,
            src=jax.device_put(blocked.src, bspec),
            local_dst=jax.device_put(blocked.local_dst, bspec),
            mask=jax.device_put(blocked.mask, bspec),
        )

    def put_hybrid(hybrid):
        # Diagonal masks are [D, n] with n the (unpadded) node axis:
        # shard axis 1 when it divides. The remainder rides the blocked
        # (einsum) form — under this path use method="hybrid-blocked";
        # the Pallas remainder kernel is an opaque custom call the
        # partitioner cannot shard.
        if hybrid is None:
            return None
        div = hybrid.masks.shape[1] % axis_size == 0
        mspec = NamedSharding(mesh, P(None, axis_name) if div else P())
        return dataclasses.replace(
            hybrid,
            masks=jax.device_put(hybrid.masks, mspec),
            remainder=put_blocked(hybrid.remainder),
        )

    def put_skew(skew):
        # Virtual rows are owner-sorted (node order), so sharding the row
        # axis keeps each shard's rows aligned with the shard owning
        # their receiver nodes; only the (node-extent) signal gather and
        # the owner-segment combine cross shards. Row padding is a
        # multiple of 8, not 128 — replicate when it does not divide
        # (tiny graphs, odd meshes), same contract as put_blocked.
        if skew is None:
            return None
        div = skew.src.shape[0] % axis_size == 0
        rspec = NamedSharding(mesh, P(axis_name) if div else P())
        return dataclasses.replace(
            skew,
            src=jax.device_put(skew.src, rspec),
            mask=jax.device_put(skew.mask, rspec),
            owner=jax.device_put(skew.owner, rspec),
            start=jax.device_put(skew.start, rspec),
            weight=(None if skew.weight is None
                    else jax.device_put(skew.weight, rspec)),
        )

    return dataclasses.replace(
        graph,
        senders=put(graph.senders),
        receivers=put(graph.receivers),
        edge_mask=put(graph.edge_mask),
        node_mask=put(graph.node_mask),
        in_degree=put(graph.in_degree),
        out_degree=put(graph.out_degree),
        neighbors=put(graph.neighbors),
        neighbor_mask=put(graph.neighbor_mask),
        edge_weight=put(graph.edge_weight),
        neighbor_weight=put(graph.neighbor_weight),
        blocked=put_blocked(graph.blocked),
        hybrid=put_hybrid(graph.hybrid),
        skew=put_skew(graph.skew),
    )


def run_auto(graph: Graph, protocol, key: jax.Array, rounds: int):
    """Run ``rounds`` protocol rounds on an auto-sharded graph.

    Identical semantics to ``engine.run`` (it IS engine.run — the shardings
    on ``graph``'s arrays make GSPMD partition the compiled program)."""
    from p2pnetwork_tpu.sim import engine

    return engine.run(graph, protocol, key, rounds)
