"""Device-mesh construction for the sharded simulation path.

The reference's "distributed backend" is hand-rolled TCP between OS processes
(SURVEY.md section 2.4); the sim backend's is a JAX device mesh with XLA
collectives over ICI/DCN. Topology scale-out is one mesh axis — a ring of
graph shards — because per-round cross-shard traffic is neighbor exchange,
which rides ICI when the axis is laid out along the physical torus.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXIS = "shards"


def ring_mesh(n_shards: Optional[int] = None, axis_name: str = DEFAULT_AXIS,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh of ``n_shards`` devices (default: all local devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_shards or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} shards but only {len(devs)} devices")
    return jax.make_mesh((n,), (axis_name,), devices=devs[:n])


def shard_spec(mesh: Mesh, axis_name: str = DEFAULT_AXIS) -> NamedSharding:
    """Sharding that splits an array's leading axis across the ring."""
    return NamedSharding(mesh, P(axis_name))
