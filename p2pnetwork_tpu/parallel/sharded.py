"""Sharded graph propagation: ring ``ppermute`` over a device mesh.

This is the TPU-native replacement for the reference's only scaling story
(one OS thread per peer, O(E) sequential socket sends, SURVEY.md section
2.4). Design (SURVEY.md sections 5 "long-context" and 7 step 4):

- **Node-partitioned state**: node ``v`` lives on shard ``v // block``;
  per-node arrays (seen flags, values, statuses) are sharded on their
  leading axis.
- **Edge-partitioned adjacency, bucketed by source shard**: shard ``d``
  holds every edge whose *receiver* it owns, grouped into ``S`` buckets by
  the *sender*'s shard, ordered by ring distance (bucket ``t`` holds edges
  from shard ``(d - t) mod S``).
- **Ring exchange**: one propagation round runs ``S`` steps. At step ``t``
  each shard holds the frontier block of shard ``(d - t) mod S`` (rotated by
  ``lax.ppermute`` each step — neighbor traffic over ICI, the ring-attention
  communication shape) and applies exactly the edge bucket that consumes it.
  After ``S`` steps every cross-shard edge has been resolved with no
  all-gather and no DCN hot spot; per-round stats come back via ``psum``.

The whole multi-round propagation (scan over rounds, ring scan inside) is
one ``shard_map``-ped, jitted XLA program — zero host round-trips;
:func:`flood_until_coverage` adds the device-side early-exit
``lax.while_loop`` so the north-star run-to-99% measurement runs multi-chip.

**Topology churn is first-class here too** — the reference's identity is
mutating a live network (connects add peers [ref: p2pnetwork/node.py:122],
errors tear connections down [ref: nodeconnection.py:123-126]), and at the
scale this path targets that must work on the sharded representation:

- :func:`with_node_liveness` / :func:`fail_nodes` /
  :func:`random_node_failures` re-mask ``bkt_mask`` / ``node_mask`` /
  ``out_degree`` device-side — same shapes, no recompile, mirroring
  sim/failures.py.
- :func:`with_capacity` reserves a **dynamic edge region**: per-(dst-shard,
  ring-step) unsorted COO slots ``[S, S, K]`` that every ring pass folds in
  alongside the static buckets, so :func:`connect`-ed links carry traffic
  immediately — no re-shard, no recompile (mirroring sim/topology.py).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exposes shard_map at the top level
    shard_map = jax.shard_map
except AttributeError:  # this image's jax 0.4.x: experimental namespace,
    # where the replication-check kwarg is still named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_exp(f, **kw)

from p2pnetwork_tpu.parallel.mesh import DEFAULT_AXIS
from p2pnetwork_tpu.sim import flightrec
from p2pnetwork_tpu.sim.graph import Graph, _round_up
from p2pnetwork_tpu.telemetry import spans
from p2pnetwork_tpu.utils import accum


# ------------------------------------------------------ halo-exchange seam

#: The ring's swappable halo-exchange backends. ``"ppermute"`` is the XLA
#: collective-permute formulation; ``"pallas"`` moves the same block as a
#: ``pltpu.make_async_remote_copy`` issued from a Pallas kernel
#: (ops/pallas_ring.py) — the DMA engine carries the halo while the
#: shard-local bucket compute runs. Both are bit-identical peers
#: (tests/test_ring.py parity sweep); ``"auto"`` routes via
#: parallel/auto.resolve_comm (pallas on TPU, ppermute elsewhere — on CPU
#: the pallas backend runs the interpreter, kept for parity CI).
COMM_BACKENDS = ("ppermute", "pallas")
DEFAULT_COMM = "ppermute"


def _resolve_comm(comm):
    # Non-string comm values are spec OBJECTS (chaos/device.FaultSpec):
    # hashable, already carrying a concrete backend, and built into a
    # comm object by _make_ring_comm — they pass through untouched.
    if not isinstance(comm, str):
        return comm
    from p2pnetwork_tpu.parallel.auto import resolve_comm

    return resolve_comm(comm)


class CommPayloadMismatch(TypeError):
    """A halo payload's shape/dtype diverged from the template its ring
    established on first shift — raised at trace time, where the caller
    can read it, instead of failing deep inside the pallas kernel or the
    XLA collective-permute lowering."""


class _RingComm:
    """One ring's halo-exchange backend: ``shift`` moves a per-shard block
    to the NEXT ring shard (``_ring_perm``), ``shift_back`` to the
    previous (the remask Horner accumulation). The ring bodies issue the
    shift BEFORE the bucket compute that consumes the resident block —
    both only read it — so the transfer's issue point precedes the
    overlap window on either backend (XLA's async collective-permute
    scheduling for ppermute; the in-kernel DMA for pallas).

    ``fused_segment_sum`` is non-None on backends that can carry the halo
    UNDER the blocked one-hot segment sum itself
    (ops/pallas_ring.ring_segment_sum: DMA started at grid step 0, the
    whole MXU edge aggregation in flight, recv-semaphore wait at the
    last step) — the fully fused ring step the MXU bucket path rides.

    Overlap honesty: on the SEGMENT bucket layouts the pallas backend's
    hop is the bare ``ring_shift`` kernel, whose start+wait both live
    inside one opaque pallas_call — no overlap with the XLA bucket
    compute outside it (ppermute, which XLA can split into
    cp-start/cp-done around independent work, can overlap there). The
    in-flight window the issue-before-compute ordering buys is real for
    ppermute everywhere and for pallas on the fused MXU path; a
    split-phase / double-buffered pallas hop for the segment layouts is
    the on-device follow-up (ROADMAP item 1).
    """

    __slots__ = ("backend", "axis_name", "axis_size", "_tpl_fwd",
                 "_tpl_back")

    #: graftquake context seam: _ring_pass threads its scan's step index
    #: through set_context only for comms that ask (chaos/device
    #: FaultyComm); the bare backends stay byte-identical to before.
    wants_step = False

    def __init__(self, backend: str, axis_name: str, axis_size: int):
        if backend not in COMM_BACKENDS:
            raise ValueError(
                f"comm must be one of {COMM_BACKENDS} (or 'auto'), got "
                f"{backend!r}")
        self.backend = backend
        self.axis_name = axis_name
        self.axis_size = axis_size
        self._tpl_fwd = None
        self._tpl_back = None

    @property
    def fuses(self) -> bool:
        """Whether this backend carries the halo UNDER the blocked
        segment sum (``fused_segment_sum`` returns non-None)."""
        return self.backend == "pallas"

    def set_context(self, round=None, step=None) -> None:
        """Fault-injection context hook (round/step of the next hops) —
        a no-op on the bare backends; chaos/device.FaultyComm records
        the tracers for its site keying."""

    def _check_payload(self, x, direction: str) -> None:
        """Validate the payload against the template this ring
        established on its first hop in ``direction`` (forward shifts
        and the reverse Horner hops legitimately carry different
        payloads — liveness masks vs degree counts — so each direction
        owns a template). Shapes are static at trace time, so the check
        is free at runtime and the error surfaces at the call site."""
        sig = (tuple(x.shape), str(x.dtype))
        slot = "_tpl_fwd" if direction == "shift" else "_tpl_back"
        tpl = getattr(self, slot)
        if tpl is None:
            setattr(self, slot, sig)
        elif tpl != sig:
            raise CommPayloadMismatch(
                f"halo payload {sig[0]}/{sig[1]} does not match the "
                f"template {tpl[0]}/{tpl[1]} this ring established on "
                f"its first {direction} — one ring moves one payload "
                "shape per direction (build a separate pass for a "
                "different payload)")

    def shift(self, x):
        self._check_payload(x, "shift")
        if self.backend == "pallas":
            from p2pnetwork_tpu.ops import pallas_ring as PR

            return PR.ring_shift(x, self.axis_name, self.axis_size)
        return jax.lax.ppermute(x, self.axis_name,
                                perm=_ring_perm(self.axis_size))

    def shift_back(self, x):
        self._check_payload(x, "shift_back")
        if self.backend == "pallas":
            from p2pnetwork_tpu.ops import pallas_ring as PR

            return PR.ring_shift(x, self.axis_name, self.axis_size,
                                 reverse=True)
        S = self.axis_size
        return jax.lax.ppermute(x, self.axis_name,
                                perm=[((i + 1) % S, i) for i in range(S)])

    def fused_segment_sum(self, rot, contrib, local_dst, block, exact):
        """``(rot_next, out)`` — the halo hop fused under the blocked
        segment sum, or None when this backend has no fused form (the
        caller then shifts and applies separately)."""
        if self.backend != "pallas":
            return None
        self._check_payload(rot, "shift")
        from p2pnetwork_tpu.ops import pallas_ring as PR

        return PR.ring_segment_sum(rot, contrib, local_dst, self.axis_name,
                                   self.axis_size, block, exact=exact)


def _make_ring_comm(comm, axis_name: str, S: int):
    """Build one ring's comm object: a backend name builds the bare
    :class:`_RingComm`; a spec object (chaos/device.FaultSpec — anything
    with ``make``) builds its wrapper. Specs are hashable, so they ride
    the same lru-cached loop factories the backend strings do."""
    if isinstance(comm, str):
        return _RingComm(comm, axis_name, S)
    return comm.make(axis_name, S)

@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedGraph:
    """A :class:`Graph` partitioned for an ``S``-shard ring.

    ``bkt_*`` have global shape ``[S, S, E_bkt]`` — leading axis sharded
    (one row per destination shard), second axis the ring step. Local edge
    indices: ``bkt_src`` into the *rotating* frontier block, ``bkt_dst`` into
    the shard's own node block. Within a bucket, edges are sorted by
    destination so segment reductions see sorted ids.

    ``dyn_*`` (optional, via :func:`with_capacity`) is the dynamic edge
    region: same ``[S, S, K]`` bucket layout, but unsorted — runtime
    :func:`connect` fills free slots and every ring pass applies the
    dynamic bucket of the resident step alongside the static one.
    """

    bkt_src: jax.Array  # i32[S, S, E_bkt]
    bkt_dst: jax.Array  # i32[S, S, E_bkt]
    bkt_mask: jax.Array  # bool[S, S, E_bkt]
    node_mask: jax.Array  # bool[S, B]
    out_degree: jax.Array  # i32[S, B]
    in_degree: jax.Array  # i32[S, B]
    n_nodes: int = dataclasses.field(metadata=dict(static=True))
    n_shards: int = dataclasses.field(metadata=dict(static=True))
    block: int = dataclasses.field(metadata=dict(static=True))
    dyn_src: Optional[jax.Array] = None  # i32[S, S, K]
    dyn_dst: Optional[jax.Array] = None  # i32[S, S, K]
    dyn_mask: Optional[jax.Array] = None  # bool[S, S, K]
    # Partner-sampling table for Gossip: GLOBAL neighbor ids per node
    # (present when the source Graph carried a neighbor table). The mask is
    # re-masked by liveness, like the single-device table.
    neighbors: Optional[jax.Array] = None  # i32[S, B, W]
    neighbors_mask: Optional[jax.Array] = None  # bool[S, B, W]
    # MXU bucket layout (shard_graph(..., mxu=True)): each static bucket's
    # edges regrouped by 128-destination block (ops/blocked.py scheme), so
    # the ring pass applies buckets as batched one-hot matmuls instead of
    # segment reductions — XLA's TPU scatter lowering is the ring path's
    # bottleneck. ``mxu_dst`` is the destination index WITHIN its 128-block.
    # Under ``hybrid=True`` these hold only the non-diagonal REMAINDER.
    mxu_src: Optional[jax.Array] = None  # i32[S, S, NB, W]
    mxu_dst: Optional[jax.Array] = None  # i32[S, S, NB, W]
    mxu_mask: Optional[jax.Array] = None  # bool[S, S, NB, W]
    # Ring-decomposed circular diagonals (shard_graph(..., hybrid=True)):
    # a global diagonal ``u = (v + off) mod n`` splits into at most two
    # STATIC (ring_step, local_shift) pieces — identical on every shard —
    # with per-shard validity masks. Applying a piece is one static
    # ``jnp.roll`` of the resident block plus a mask: pure VPU traffic,
    # the sharded mirror of ops/diag.py's gather-free fast path.
    diag_masks: Optional[jax.Array] = None  # bool[S, P, B]
    diag_pieces: Tuple[Tuple[int, int], ...] = dataclasses.field(
        default=(), metadata=dict(static=True)
    )  # ((ring_step, local_shift), ...) per mask row
    #: Destination-block width of the MXU layout (512 cuts Poisson padding
    #: waste vs 128 at the cost of a wider one-hot, like ops/diag.py).
    mxu_block: int = dataclasses.field(default=128,
                                       metadata=dict(static=True))
    # Per-shard sender-CSR view for frontier-sparse traversal
    # (shard_graph(source_csr=True)): for this shard's edges (dst-owned),
    # positions into the FLATTENED bucket arrays (``ring_step * E_bkt +
    # slot``) grouped by GLOBAL sender id — ``csr_pos[d,
    # csr_offsets[d, u] : csr_offsets[d, u + 1]]`` are sender ``u``'s edges
    # into shard ``d``. Gathering bkt_mask/bkt_dst through these positions
    # inherits liveness re-masks and disconnects with no rebuild. Row
    # extents are build-time; out-of-row slots must be masked by the
    # consumer (padding entries stay in bounds but can alias live slots).
    csr_pos: Optional[jax.Array] = None  # i32[S, E_s]
    csr_offsets: Optional[jax.Array] = None  # i32[S, S*block + 1]
    #: Widest per-(sender, dst-shard) build-time row, 0 without the view.
    csr_span: int = dataclasses.field(default=0, metadata=dict(static=True))

    @property
    def n_nodes_padded(self) -> int:
        return self.n_shards * self.block

    @property
    def dyn_capacity(self) -> int:
        return 0 if self.dyn_src is None else self.dyn_src.shape[-1]


def _dyn_or_empty(sg: ShardedGraph):
    """The dynamic bucket triple, or zero-width placeholders (K == 0 makes
    the ring pass skip the dynamic group at trace time — one code path,
    no extra compile-cache key)."""
    if sg.dyn_src is not None:
        return sg.dyn_src, sg.dyn_dst, sg.dyn_mask
    S = sg.n_shards
    return (
        jnp.zeros((S, S, 0), jnp.int32),
        jnp.zeros((S, S, 0), jnp.int32),
        jnp.zeros((S, S, 0), bool),
    )


def _diag_masks_or_empty(sg: ShardedGraph):
    """The diagonal piece masks, or a zero-piece placeholder (``P == 0``
    pairs with the empty static ``diag_pieces`` tuple)."""
    if sg.diag_masks is not None:
        return sg.diag_masks
    return jnp.zeros((sg.n_shards, 0, sg.block), bool)


def _mxu_or_empty(sg: ShardedGraph):
    """The MXU bucket triple, or zero-width placeholders (W == 0 selects
    the segment static group at trace time)."""
    if sg.mxu_src is not None:
        return sg.mxu_src, sg.mxu_dst, sg.mxu_mask
    S = sg.n_shards
    return (
        jnp.zeros((S, S, 1, 0), jnp.int32),
        jnp.zeros((S, S, 1, 0), jnp.int32),
        jnp.zeros((S, S, 1, 0), bool),
    )


def _extract_ring_diagonals(senders, receivers, n, S, block, max_diags,
                            min_count):
    """Select dominant circular diagonals and decompose each into static
    ring pieces (host-side; see ShardedGraph.diag_pieces).

    Returns ``(pieces, masks [S, P, block], diag_sel)`` where ``diag_sel``
    flags the edges covered (the rest go to the bucket remainder). Edges
    whose signed offset wraps the real-node boundary (``v + off_s`` outside
    ``[0, n)``) stay in the remainder — only the uniform no-wrap body of a
    diagonal has the shard-invariant piece structure.
    """
    from p2pnetwork_tpu.ops.diag import select_diagonals

    kept, per_sel, diag_sel = select_diagonals(
        senders, receivers, n, max_diags, min_count
    )
    pieces = []
    mask_rows = []
    for o, sel in zip(kept, per_sel):
        off_s = o if o <= n // 2 else o - n
        v = receivers[sel].astype(np.int64)
        nowrap = (v + off_s >= 0) & (v + off_s < n)
        dropped = sel[~nowrap]
        diag_sel[dropped] = False  # wrap edges ride the remainder
        sel = sel[nowrap]
        if not sel.size:
            continue
        dmask = np.zeros(S * block, dtype=bool)
        dmask[receivers[sel]] = True
        dmask = dmask.reshape(S, block)
        q, r = divmod(off_s, block)  # floor division: r in [0, block)
        j = np.arange(block)
        piece_a = dmask & (j + r < block)[None, :]
        piece_b = dmask & (j + r >= block)[None, :]
        t_a = (-q) % S
        t_b = (-q - 1) % S
        if S == 1 or t_a == t_b:
            if piece_a.any() or piece_b.any():
                pieces.append((t_a, int(r)))  # graftlint: ignore[host-sync-in-loop] -- r is a host int from divmod
                mask_rows.append(dmask)
        else:
            if piece_a.any():
                pieces.append((t_a, int(r)))  # graftlint: ignore[host-sync-in-loop] -- host int
                mask_rows.append(piece_a)
            if piece_b.any():
                pieces.append((t_b, int(r)))  # graftlint: ignore[host-sync-in-loop] -- host int
                mask_rows.append(piece_b)
    if not pieces:
        return (), None, diag_sel
    masks = np.stack(mask_rows, axis=1)  # [S, P, block]
    return tuple(pieces), masks, diag_sel


def shard_graph(graph: Graph, mesh: Mesh, axis_name: str = DEFAULT_AXIS,
                edge_pad_multiple: int = 128, mxu: bool = False,
                hybrid: bool = False, max_diags: int = 64,
                min_count: Optional[int] = None,
                source_csr: bool = False) -> ShardedGraph:
    """Partition ``graph`` for ``mesh`` (host-side; one-off setup).

    Nodes are split into ``S`` contiguous blocks. Every active edge lands in
    bucket ``(dst_shard, ring_step)`` where ``ring_step = (dst_shard -
    src_shard) mod S`` — the step of the ring rotation at which the sender's
    frontier block is resident on the receiver's shard.

    A graph carrying live dynamic edges (sim/topology.py) is sharded
    losslessly: its runtime links are folded into the static buckets (this
    IS the documented consolidation path — re-shard when churn accumulates).

    ``mxu=True`` additionally builds the per-bucket one-hot-matmul layout
    (see ``ShardedGraph.mxu_src``) — on TPU the ring pass then runs on the
    MXU instead of XLA's scatter lowering of segment reductions (~2x per
    chip at 1M nodes; measured in benchmarks/ladder.py).

    ``source_csr=True`` additionally builds the per-shard sender-CSR view
    (``csr_pos``/``csr_offsets``) that the frontier-adaptive coverage loop
    gathers small frontiers through (see :func:`flood_until_coverage`'s
    ``adaptive_k``).
    """
    S = mesh.shape[axis_name]
    emask = np.asarray(graph.edge_mask)
    senders = np.asarray(graph.senders)[emask]
    receivers = np.asarray(graph.receivers)[emask]
    if graph.dyn_mask is not None:
        dmask = np.asarray(graph.dyn_mask)
        senders = np.concatenate([senders, np.asarray(graph.dyn_senders)[dmask]])
        receivers = np.concatenate([receivers, np.asarray(graph.dyn_receivers)[dmask]])

    block = _round_up(graph.n_nodes_padded, S) // S

    # Diagonal extraction must precede bucketing (the selection indexes the
    # unsorted edge arrays); the covered edges leave the APPLIED remainder
    # but stay in the bkt_* truth arrays below (degrees, probe, remask).
    diag_pieces: Tuple[Tuple[int, int], ...] = ()
    diag_masks = None
    if hybrid:
        diag_pieces, diag_masks, diag_sel = _extract_ring_diagonals(
            senders, receivers, graph.n_nodes, S, block, max_diags, min_count
        )
        mxu = True  # the remainder rides the MXU buckets
    else:
        diag_sel = np.zeros(senders.shape[0], dtype=bool)

    def _bucketize(s_arr, r_arr):
        """Sort edges by (bucket, local dst); return sorted arrays, bucket
        offsets (bucket = dst_shard * S + ring_step), sorted bucket ids,
        and the sort order."""
        flat = (r_arr // block) * S + ((r_arr // block) - (s_arr // block)) % S
        order = np.lexsort((r_arr, flat))
        s_arr, r_arr, flat = s_arr[order], r_arr[order], flat[order]
        offs = np.zeros(S * S + 1, dtype=np.int64)
        np.cumsum(np.bincount(flat, minlength=S * S), out=offs[1:])
        return s_arr, r_arr, offs, flat, order

    senders_b, receivers_b, offsets, flat_b, order_b = _bucketize(
        senders, receivers
    )
    e_bkt = _round_up(
        max(int(np.diff(offsets).max()), 1), edge_pad_multiple
    )
    bkt_src = np.zeros((S, S, e_bkt), dtype=np.int32)
    # Pad destinations with block-1 so each bucket stays dst-sorted — the
    # segment reductions in the ring body promise indices_are_sorted=True.
    bkt_dst = np.full((S, S, e_bkt), block - 1, dtype=np.int32)
    bkt_mask = np.zeros((S, S, e_bkt), dtype=bool)
    for d in range(S):
        for t in range(S):
            b = d * S + t
            lo, hi = offsets[b], offsets[b + 1]
            cnt = hi - lo
            bkt_src[d, t, :cnt] = senders_b[lo:hi] % block
            bkt_dst[d, t, :cnt] = receivers_b[lo:hi] % block
            bkt_mask[d, t, :cnt] = True

    mxu_src = mxu_dst = mxu_mask = None
    mxu_block = 512  # ops/diag.py's remainder block: less padding waste
    if mxu:
        from p2pnetwork_tpu.ops.blocked import build_blocked_arrays_np

        # A subset of the already-bucket-sorted arrays stays sorted — no
        # second O(E log E) lexsort for the remainder.
        ks = ~diag_sel[order_b]
        rem_s, rem_r = senders_b[ks], receivers_b[ks]
        rem_offs = np.zeros(S * S + 1, dtype=np.int64)
        np.cumsum(np.bincount(flat_b[ks], minlength=S * S), out=rem_offs[1:])
        per_bucket = []
        for d in range(S):
            for t in range(S):
                b = d * S + t
                lo_, hi_ = rem_offs[b], rem_offs[b + 1]
                per_bucket.append(build_blocked_arrays_np(
                    (rem_s[lo_:hi_] % block).astype(np.int32),
                    (rem_r[lo_:hi_] % block).astype(np.int32),
                    block, mxu_block,
                ))
        nb = max(bs.shape[0] for bs, _, _ in per_bucket)
        w = max(bs.shape[1] for bs, _, _ in per_bucket)
        mxu_src = np.zeros((S, S, nb, w), np.int32)
        mxu_dst = np.zeros((S, S, nb, w), np.int32)
        mxu_mask = np.zeros((S, S, nb, w), bool)
        for d in range(S):
            for t in range(S):
                bs, bd, bm = per_bucket[d * S + t]
                r, c = bs.shape
                mxu_src[d, t, :r, :c] = bs
                mxu_dst[d, t, :r, :c] = bd
                mxu_mask[d, t, :r, :c] = bm

    csr_pos = csr_offsets = None
    csr_span = 0
    if source_csr:
        from p2pnetwork_tpu import native

        n_g = S * block
        rows_pos = []
        counts = np.zeros((S, n_g), dtype=np.int64)
        for d in range(S):
            # This shard's live bucket slots, flattened (t * e_bkt + slot),
            # keyed by the GLOBAL sender id reconstructed from the ring
            # step: step t holds senders of shard (d - t) mod S.
            t_idx, slot_idx = np.nonzero(bkt_mask[d])
            g_send = (
                ((d - t_idx) % S) * block + bkt_src[d, t_idx, slot_idx]
            ).astype(np.int32)
            pos = (t_idx * e_bkt + slot_idx).astype(np.int32)
            _, pos_sorted = native.sort_pairs(g_send, pos)
            rows_pos.append(pos_sorted)
            counts[d] = np.bincount(g_send, minlength=n_g)
        e_s = _round_up(max(max(p.size for p in rows_pos), 1),
                        edge_pad_multiple)
        csr_pos = np.zeros((S, e_s), dtype=np.int32)
        for d in range(S):
            csr_pos[d, : rows_pos[d].size] = rows_pos[d]
        csr_offsets = np.zeros((S, n_g + 1), dtype=np.int32)
        np.cumsum(counts, axis=1, out=csr_offsets[:, 1:])
        csr_span = int(counts.max()) if counts.size else 0

    pad_n = S * block - graph.n_nodes_padded
    node_mask = np.pad(np.asarray(graph.node_mask), (0, pad_n))
    out_degree = np.pad(np.asarray(graph.out_degree), (0, pad_n))
    in_degree = np.pad(np.asarray(graph.in_degree), (0, pad_n))
    neighbors = neighbors_mask = None
    if graph.neighbors is not None:
        neighbors = np.pad(np.asarray(graph.neighbors), ((0, pad_n), (0, 0)))
        neighbors_mask = np.pad(
            np.asarray(graph.neighbor_mask), ((0, pad_n), (0, 0))
        )

    shard = NamedSharding(mesh, P(axis_name))
    dev = lambda x: jax.device_put(x, shard)  # noqa: E731
    return ShardedGraph(
        bkt_src=dev(bkt_src),
        bkt_dst=dev(bkt_dst),
        bkt_mask=dev(bkt_mask),
        node_mask=dev(node_mask.reshape(S, block)),
        out_degree=dev(out_degree.reshape(S, block).astype(np.int32)),
        in_degree=dev(in_degree.reshape(S, block).astype(np.int32)),
        n_nodes=graph.n_nodes,
        n_shards=S,
        block=block,
        neighbors=None if neighbors is None else dev(
            neighbors.reshape(S, block, -1)
        ),
        neighbors_mask=None if neighbors_mask is None else dev(
            neighbors_mask.reshape(S, block, -1)
        ),
        mxu_src=None if mxu_src is None else dev(mxu_src),
        mxu_dst=None if mxu_dst is None else dev(mxu_dst),
        mxu_mask=None if mxu_mask is None else dev(mxu_mask),
        diag_masks=None if diag_masks is None else dev(diag_masks),
        diag_pieces=diag_pieces,
        mxu_block=mxu_block,
        csr_pos=None if csr_pos is None else dev(csr_pos),
        csr_offsets=None if csr_offsets is None else dev(csr_offsets),
        csr_span=csr_span,
    )


# --------------------------------------------------------------- churn ops


def with_capacity(sg: ShardedGraph, extra_edges: int) -> ShardedGraph:
    """Reserve ``extra_edges`` dynamic slots per (dst-shard, ring-step)
    bucket — any distribution of that many directed links is guaranteed to
    fit whichever bucket it lands in. Host-side, one-off; growing an
    existing region preserves every runtime link."""
    K = _round_up(max(extra_edges, 1), 8)
    S = sg.n_shards
    # Commit the region to the mesh up front — uncommitted/single-device
    # arrays mixed with sharded operands are rejected under shard_map.
    shard = NamedSharding(_mesh_of(sg), P(_mesh_of(sg).axis_names[0]))
    if sg.dyn_src is not None:
        grow = K
        pad = lambda x: jax.device_put(  # noqa: E731
            jnp.pad(x, ((0, 0), (0, 0), (0, grow))), shard)
        return dataclasses.replace(
            sg,
            dyn_src=pad(sg.dyn_src),
            dyn_dst=pad(sg.dyn_dst),
            dyn_mask=pad(sg.dyn_mask),
        )
    return dataclasses.replace(
        sg,
        dyn_src=jax.device_put(jnp.zeros((S, S, K), jnp.int32), shard),
        dyn_dst=jax.device_put(jnp.zeros((S, S, K), jnp.int32), shard),
        dyn_mask=jax.device_put(jnp.zeros((S, S, K), bool), shard),
    )


def _mesh_of(sg: ShardedGraph) -> Mesh:
    """The mesh the graph's arrays live on (set by shard_graph's
    device_put; churn ops run shard_map programs over it)."""
    mesh = sg.bkt_src.sharding.mesh
    if isinstance(mesh, jax.sharding.AbstractMesh):  # pragma: no cover
        raise ValueError("ShardedGraph arrays carry an abstract mesh; "
                         "device_put them on a concrete mesh first")
    return mesh


def _remask_body(axis_name, S, block, pieces, mxu_block, comm,
                 bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                 mxu_src, mxu_dst, mxu_mask, diag_masks,
                 neighbors, neighbors_mask, node_mask, alive):
    """Per-shard liveness re-mask: an edge survives iff both endpoints do.

    Runs under shard_map. The source block of bucket ``t`` is the block
    resident after ``t`` ring rotations, so the per-step source liveness is
    collected with the same halo-exchange ring the propagation uses
    (``comm`` seam — ppermute or the Pallas DMA kernel). Out-degree
    counts are computed per bucket on the receiver's shard, then carried
    back to the sender's shard with a reverse-rotating Horner accumulation:
    ``out[s] = sum_t cnt[(s+t) mod S, t]``.
    """
    comm_obj = _make_ring_comm(comm, axis_name, S)
    nm = node_mask[0] & alive[0]  # [B]

    # masks_by_t[t] = liveness of the block resident at ring step t
    # (= shard (d - t) mod S's block, exactly what bkt_src[t] indexes).
    def collect(rot, _):
        return comm_obj.shift(rot), rot

    _, masks_by_t = jax.lax.scan(collect, nm, None, length=S)

    def remask_group(src, dst, mask):  # [S, W] each
        if src.shape[-1] == 0:
            zero = jnp.zeros((S, block), jnp.int32)
            return mask, zero, zero[0]
        src_alive = jnp.take_along_axis(masks_by_t, src, axis=1)
        dst_alive = nm[dst]
        mask = mask & src_alive & dst_alive
        cnt = jax.vmap(
            lambda m, s: jax.ops.segment_sum(
                m.astype(jnp.int32), s, num_segments=block
            )
        )(mask, src)  # [S_t, B] — counts for the sender block of each step
        # In-degrees are local: every bucket's receivers are this shard's.
        cnt_in = jax.vmap(
            lambda m, r: jax.ops.segment_sum(
                m.astype(jnp.int32), r, num_segments=block
            )
        )(mask, dst).sum(axis=0)  # [B]
        return mask, cnt, cnt_in

    bkt_mask_b, cnt_s, in_s = remask_group(bkt_src[0], bkt_dst[0], bkt_mask[0])
    dyn_mask_b, cnt_d, in_d = remask_group(dyn_src[0], dyn_dst[0], dyn_mask[0])
    cnt = cnt_s + cnt_d  # [S_t, B]
    in_degree = in_s + in_d  # [B]

    # Horner: acc <- cnt_t + rot_back(acc), t = S-1 .. 0, where rot_back
    # moves each block one shard backward along the ring.
    def horner(acc, cnt_t):
        return cnt_t + comm_obj.shift_back(acc), None

    if S > 1:
        out_degree, _ = jax.lax.scan(horner, cnt[S - 1], cnt[: S - 1],
                                     reverse=True)
    else:
        out_degree = cnt[0]

    # MXU bucket re-mask (mirrors sim/failures._remask_blocked): sources by
    # ring-step liveness, destinations by the local mxu_block layout.
    if mxu_src.shape[-1] > 0:
        _, nb, w = mxu_src.shape[1:]
        src_alive = jnp.take_along_axis(
            masks_by_t, mxu_src[0].reshape(S, nb * w), axis=1
        ).reshape(S, nb, w)
        gd = jnp.minimum(
            jnp.arange(nb, dtype=jnp.int32)[None, :, None] * mxu_block
            + mxu_dst[0],
            block - 1,
        )
        mxu_mask_b = mxu_mask[0] & src_alive & nm[gd]
    else:
        mxu_mask_b = mxu_mask[0]

    # Diagonal-piece re-mask: a piece edge u -> v needs v alive (nm) and
    # u alive — u sits at local (j + r) % B of the block resident at the
    # piece's ring step, i.e. the same static roll the apply uses.
    if pieces:
        dm = diag_masks[0]
        rows = [dm[pi] & nm & jnp.roll(masks_by_t[tp], -r)
                for pi, (tp, r) in enumerate(pieces)]
        diag_masks_b = jnp.stack(rows, axis=0)
    else:
        diag_masks_b = diag_masks[0]

    # Partner-table re-mask (mirrors sim/failures.py's
    # `neighbor_mask & node_mask[:, None] & node_mask[neighbors]`): the
    # neighbor ids are global, so their liveness comes from the collected
    # ring blocks — neighbor p lives on shard p // block, resident at ring
    # step (my - p // block) mod S.
    my = jax.lax.axis_index(axis_name)
    if neighbors.shape[-1] > 0:
        p_shard = neighbors[0] // block  # [B, W]
        p_local = neighbors[0] % block
        nbr_alive = masks_by_t[(my - p_shard) % S, p_local]
        nbr_mask = neighbors_mask[0] & nm[:, None] & nbr_alive
    else:
        nbr_mask = neighbors_mask[0]
    return (bkt_mask_b[None], dyn_mask_b[None], mxu_mask_b[None],
            diag_masks_b[None], nm[None], out_degree[None], in_degree[None],
            nbr_mask[None])


@functools.lru_cache(maxsize=64)
def _remask_fn(mesh: Mesh, axis_name: str, S: int, block: int, pieces=(),
               mxu_block: int = 128, comm: str = DEFAULT_COMM):
    body = functools.partial(_remask_body, axis_name, S, block, pieces,
                             mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False under the pallas backend: see the note on the
    # ring-body factories (the DMA kernel's lowering and vma typing).
    kw = {} if comm == "ppermute" else {"check_vma": False}
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec,) * 14,
        out_specs=(spec,) * 8,
        **kw,
    )
    return jax.jit(fn)


def with_node_liveness(sg: ShardedGraph, alive: jax.Array, *,
                       comm: str = DEFAULT_COMM) -> ShardedGraph:
    """Apply a liveness mask (False = failed) to the sharded graph —
    the sharded mirror of sim/failures.with_node_liveness. ``alive`` is
    bool, global ``[S*block]`` or already-blocked ``[S, block]``.

    Entirely device-side, shapes unchanged: the compiled flood/SIR/coverage
    programs are NOT recompiled, the next round simply routes around the
    damage — same no-recompile property as the single-device path.
    ``comm`` selects the halo-exchange backend of the liveness-collection
    ring (see :data:`COMM_BACKENDS`); the re-masked graph is backend-
    independent, so churn and propagation may mix backends freely.
    """
    alive = jnp.asarray(alive).reshape(sg.n_shards, sg.block)
    mesh = _mesh_of(sg)
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    if sg.neighbors is not None:
        neighbors, neighbors_mask = sg.neighbors, sg.neighbors_mask
    else:
        neighbors = jnp.zeros((sg.n_shards, sg.block, 0), jnp.int32)
        neighbors_mask = jnp.zeros((sg.n_shards, sg.block, 0), bool)
    fn = _remask_fn(mesh, mesh.axis_names[0], sg.n_shards, sg.block,
                    sg.diag_pieces, sg.mxu_block, _resolve_comm(comm))
    (bkt_mask, dyn_mask, mxu_mask, diag_masks, node_mask, out_degree,
     in_degree, nbr_mask) = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
        dyn_src, dyn_dst, dyn_mask, mxu_src, mxu_dst, mxu_mask,
        _diag_masks_or_empty(sg),
        neighbors, neighbors_mask, sg.node_mask, alive,
    )
    return dataclasses.replace(
        sg,
        bkt_mask=bkt_mask,
        node_mask=node_mask,
        out_degree=out_degree,
        in_degree=in_degree,
        dyn_mask=dyn_mask if sg.dyn_mask is not None else None,
        neighbors_mask=nbr_mask if sg.neighbors_mask is not None else None,
        mxu_mask=mxu_mask if sg.mxu_mask is not None else None,
        diag_masks=diag_masks if sg.diag_masks is not None else None,
    )


def fail_nodes(sg: ShardedGraph, node_ids) -> ShardedGraph:
    """Fail-stop the given global node ids (sharded mirror of
    sim/failures.fail_nodes)."""
    ids = np.asarray(node_ids, dtype=np.int64)
    if ids.size and (ids.min() < 0 or ids.max() >= sg.n_nodes_padded):
        raise ValueError(f"node id out of range [0, {sg.n_nodes_padded})")
    alive = jnp.ones(sg.n_nodes_padded, bool).at[
        jnp.asarray(ids, dtype=jnp.int32)].set(False)
    return with_node_liveness(sg, alive)


def random_node_failures(sg: ShardedGraph, key: jax.Array,
                         frac: float) -> ShardedGraph:
    """Fail each live node independently with probability ``frac``. Draws
    over the full padded population, so when ``S*block == n_pad`` the
    failure set is bit-identical to sim/failures.random_node_failures with
    the same key."""
    alive = ~(
        jax.random.bernoulli(key, frac, (sg.n_nodes_padded,)).reshape(
            sg.n_shards, sg.block
        )
        & sg.node_mask
    )
    return with_node_liveness(sg, alive)


def _pad_queries(S, *arrays, multiple=16):
    """Pad query vectors to a length multiple (fewer retraces across call
    sites). Padding rows get dst shard ``S`` — matching no shard, they are
    inert in every probe/scatter body."""
    q = arrays[0].size
    q_pad = _round_up(max(q, 1), multiple)
    out = []
    for i, a in enumerate(arrays):
        fill = S if i == 0 else 0  # first array is the dst-shard vector
        out.append(np.pad(a, (0, q_pad - q), constant_values=fill))
    return out


def _member_body(axis_name, S,
                 bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                 d, t, sl, rl):
    """Replicated queries in, replicated answers out: each shard probes the
    buckets it owns (d == my shard); a psum ORs the per-shard verdicts."""
    my = jax.lax.axis_index(axis_name)
    mine = d == my

    def probe(src, dst, m):  # [S_t, W] locals
        if src.shape[-1] == 0:
            return jnp.zeros(d.shape, bool)
        rows_s = src[0][t]  # [Q, W] — t is a local (unsharded) axis
        rows_d = dst[0][t]
        rows_m = m[0][t]
        return ((rows_s == sl[:, None]) & (rows_d == rl[:, None]) & rows_m
                ).any(axis=1)

    hit = (probe(bkt_src, bkt_dst, bkt_mask)
           | probe(dyn_src, dyn_dst, dyn_mask)) & mine
    return jax.lax.psum(hit.astype(jnp.int32), axis_name) > 0


@functools.lru_cache(maxsize=64)
def _member_fn(mesh: Mesh, axis_name: str, S: int):
    body = functools.partial(_member_body, axis_name, S)
    spec = P(axis_name)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec,) * 6 + (P(),) * 4,
        out_specs=P(),
    )
    return jax.jit(fn)


def _scatter_body(axis_name, S, block,
                  dyn_src, dyn_dst, dyn_mask, out_degree, in_degree,
                  d, t, k, sl, rl):
    """Write new dynamic edges into the owning shard's bucket slots and bump
    the sender shard's out-degrees / receiver shard's in-degrees. Non-owned
    queries route to an out-of-bounds row and are dropped by the scatter."""
    my = jax.lax.axis_index(axis_name)
    mine = d == my
    tt = jnp.where(mine, t, S)  # OOB row -> dropped
    ds = dyn_src[0].at[tt, k].set(sl, mode="drop")
    dd = dyn_dst[0].at[tt, k].set(rl, mode="drop")
    dm = dyn_mask[0].at[tt, k].set(True, mode="drop")
    sender_mine = ((d - t) % S == my) & (d < S)
    bb = jnp.where(sender_mine, sl, block)  # OOB -> dropped
    od = out_degree[0].at[bb].add(1, mode="drop")
    ii = jnp.where(mine, rl, block)
    ideg = in_degree[0].at[ii].add(1, mode="drop")
    return ds[None], dd[None], dm[None], od[None], ideg[None]


@functools.lru_cache(maxsize=64)
def _scatter_fn(mesh: Mesh, axis_name: str, S: int, block: int):
    body = functools.partial(_scatter_body, axis_name, S, block)
    spec = P(axis_name)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec,) * 5 + (P(),) * 5,
        out_specs=(spec,) * 5,
    )
    return jax.jit(fn)


def _host_fetch(x) -> np.ndarray:
    """Host copy of a possibly multi-process-sharded array.

    ``np.asarray`` on an array whose shards live on OTHER processes is an
    error by design; the cross-process case all-gathers first (every
    process calls this at the same program point — connect's host-side
    orchestration is SPMD like everything else)."""
    if x.is_fully_addressable:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def connect(sg: ShardedGraph, senders, receivers, *,
            undirected: bool = True) -> ShardedGraph:
    """Add links between global node ids at runtime (sharded mirror of
    sim/topology.connect; the population analog of ``connect_with_node``
    [ref: p2pnetwork/node.py:122]).

    Each new directed edge lands in its (dst-shard, ring-step) dynamic
    bucket; already-existing pairs (static or dynamic) are dropped, like
    the reference's duplicate-connect no-op [ref: node.py:136-139]. The
    existence probe and the slot writes are shard_map programs (each shard
    handles the queries it owns); only slot allocation is orchestrated
    host-side over the small ``[S, S, K]`` occupancy mask — connect is an
    event, not the hot path.
    """
    if sg.dyn_src is None:
        raise ValueError(
            "no dynamic edge capacity: reserve slots with "
            "sharded.with_capacity(sg, extra_edges=...) first"
        )
    S, B, K = sg.n_shards, sg.block, sg.dyn_capacity
    mesh = _mesh_of(sg)
    axis = mesh.axis_names[0]
    s = np.asarray(senders, np.int64).reshape(-1)
    r = np.asarray(receivers, np.int64).reshape(-1)
    if s.size and (min(s.min(), r.min()) < 0
                   or max(s.max(), r.max()) >= sg.n_nodes_padded):
        raise ValueError(f"node id out of range [0, {sg.n_nodes_padded})")
    if undirected:
        s, r = np.concatenate([s, r]), np.concatenate([r, s])

    # Drop duplicates within the batch (first occurrence wins).
    _, first = np.unique(s * np.int64(sg.n_nodes_padded) + r, return_index=True)
    keep = np.zeros(s.size, bool)
    keep[first] = True

    # Dead endpoints reject the link (sim/topology.connect parity — the
    # reference's connect to a crashed peer fails [ref: node.py:173-176]).
    alive = _host_fetch(sg.node_mask).reshape(-1)
    keep &= alive[s] & alive[r]

    # Drop pairs that already exist — each shard probes the exact bucket
    # the pair would occupy (O(Q * E_bkt) on its own rows, not O(Q * E)).
    d = (r // B).astype(np.int32)
    t = ((d - s // B) % S).astype(np.int32)
    sl = (s % B).astype(np.int32)
    rl = (r % B).astype(np.int32)
    dp, tp, slp, rlp = _pad_queries(S, d, t, sl, rl)
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    exists = np.asarray(_member_fn(mesh, axis, S)(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        jnp.asarray(dp), jnp.asarray(tp), jnp.asarray(slp), jnp.asarray(rlp),
    ))[: d.size]
    keep &= ~exists
    if not keep.any():
        return sg

    d, t, sl, rl = d[keep], t[keep], sl[keep], rl[keep]
    # Free-slot allocation per bucket (host-side; dyn_mask is S*S*K bools).
    dmask = _host_fetch(sg.dyn_mask).copy()  # mutable copy
    slots = np.empty(d.size, np.int32)
    for i in range(d.size):
        free = np.nonzero(~dmask[d[i], t[i]])[0]
        if not free.size:
            raise ValueError(
                f"dynamic bucket ({d[i]}, {t[i]}) full ({K} slots); "
                f"re-shard via shard_graph (consolidation) or reserve more "
                f"via with_capacity"
            )
        slots[i] = free[0]
        dmask[d[i], t[i], free[0]] = True

    dp, tp, kp, slp, rlp = _pad_queries(S, d, t, slots, sl, rl)
    dyn_src, dyn_dst, dyn_mask, out_degree, in_degree = _scatter_fn(
        mesh, axis, S, B
    )(
        sg.dyn_src, sg.dyn_dst, sg.dyn_mask, sg.out_degree, sg.in_degree,
        jnp.asarray(dp), jnp.asarray(tp), jnp.asarray(kp),
        jnp.asarray(slp), jnp.asarray(rlp),
    )
    return dataclasses.replace(
        sg, dyn_src=dyn_src, dyn_dst=dyn_dst, dyn_mask=dyn_mask,
        out_degree=out_degree, in_degree=in_degree,
    )


def _unscatter_body(axis_name, S, block,
                    dyn_src, dyn_dst, dyn_mask, out_degree, in_degree,
                    d, t, sl, rl):
    """Clear matching dynamic edges on the owning shard; psum the removal
    verdicts so the sender's shard can decrement its out-degrees."""
    my = jax.lax.axis_index(axis_name)
    mine = d == my
    rows_s = dyn_src[0][t]  # [Q, K]
    rows_d = dyn_dst[0][t]
    rows_m = dyn_mask[0][t]
    hit = (rows_s == sl[:, None]) & (rows_d == rl[:, None]) & rows_m
    hit = hit & mine[:, None]
    tt = jnp.where(mine, t, S)
    dm = dyn_mask[0].at[tt].min(~hit, mode="drop")
    removed = jax.lax.psum(hit.any(axis=1).astype(jnp.int32), axis_name)
    sender_mine = ((d - t) % S == my) & (d < S)
    bb = jnp.where(sender_mine, sl, block)
    od = out_degree[0].at[bb].add(-removed, mode="drop")
    ii = jnp.where(mine, rl, block)
    ideg = in_degree[0].at[ii].add(-removed, mode="drop")
    return dm[None], od[None], ideg[None]


@functools.lru_cache(maxsize=64)
def _unscatter_fn(mesh: Mesh, axis_name: str, S: int, block: int):
    body = functools.partial(_unscatter_body, axis_name, S, block)
    spec = P(axis_name)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec,) * 5 + (P(),) * 4,
        out_specs=(spec,) * 3,
    )
    return jax.jit(fn)


def disconnect(sg: ShardedGraph, senders, receivers, *,
               undirected: bool = True) -> ShardedGraph:
    """Remove runtime links (matched by endpoint pair; static edges are
    removed with :func:`fail_nodes` / a re-shard)."""
    if sg.dyn_src is None:
        raise ValueError("graph has no dynamic edge region")
    S, B = sg.n_shards, sg.block
    mesh = _mesh_of(sg)
    s = np.asarray(senders, np.int64).reshape(-1)
    r = np.asarray(receivers, np.int64).reshape(-1)
    if undirected:
        s, r = np.concatenate([s, r]), np.concatenate([r, s])
    # Dedup queries: a pair listed twice must decrement degrees once.
    _, first = np.unique(s * np.int64(sg.n_nodes_padded) + r, return_index=True)
    s, r = s[np.sort(first)], r[np.sort(first)]
    d = (r // B).astype(np.int32)
    t = ((d - s // B) % S).astype(np.int32)
    sl = (s % B).astype(np.int32)
    rl = (r % B).astype(np.int32)
    dp, tp, slp, rlp = _pad_queries(S, d, t, sl, rl)
    dyn_mask, out_degree, in_degree = _unscatter_fn(
        mesh, mesh.axis_names[0], S, B
    )(
        sg.dyn_src, sg.dyn_dst, sg.dyn_mask, sg.out_degree, sg.in_degree,
        jnp.asarray(dp), jnp.asarray(tp), jnp.asarray(slp), jnp.asarray(rlp),
    )
    return dataclasses.replace(sg, dyn_mask=dyn_mask, out_degree=out_degree,
                               in_degree=in_degree)


def init_state(sg: ShardedGraph, protocol, key: jax.Array):
    """The sharded initial state for a protocol — what ``protocol.init``
    produces on the engine path, laid out ``[S, block]``. Flood ->
    ``(seen, frontier)``; SIR -> ``status``; Gossip -> ``values``;
    HopDistance -> ``(dist, frontier, round)``; PageRank -> ``ranks``;
    PushSum -> ``(s, w)``."""
    from p2pnetwork_tpu.models.flood import Flood
    from p2pnetwork_tpu.models.gossip import Gossip
    from p2pnetwork_tpu.models.hopdist import HopDistance
    from p2pnetwork_tpu.models.pagerank import PageRank
    from p2pnetwork_tpu.models.pushsum import PushSum
    from p2pnetwork_tpu.models.sir import SIR

    S, block = sg.n_shards, sg.block
    if isinstance(protocol, Flood):
        seed = _flood_seed(sg, protocol.source)
        return (seed, seed)
    if isinstance(protocol, SIR):
        source = protocol.source
        return (
            jnp.zeros((S, block), dtype=jnp.int32)
            .at[source // block, source % block].set(1)
        ) * sg.node_mask
    if isinstance(protocol, Gossip):
        vals = jax.random.normal(key, (sg.n_nodes_padded,), dtype=jnp.float32)
        return vals.reshape(S, block) * sg.node_mask
    if isinstance(protocol, HopDistance):
        seed = _flood_seed(sg, protocol.source)
        dist = jnp.where(seed, 0, -1).astype(jnp.int32)
        return (dist, seed, jnp.int32(0))
    if isinstance(protocol, PageRank):
        mask_f = sg.node_mask.astype(jnp.float32)
        return mask_f / jnp.maximum(jnp.sum(mask_f), 1.0)
    if isinstance(protocol, PushSum):
        vals = jax.random.normal(key, (sg.n_nodes_padded,), dtype=jnp.float32)
        mask_f = sg.node_mask.astype(jnp.float32)
        return (vals.reshape(S, block) * mask_f, mask_f)
    raise ValueError(
        f"the sharded path implements Flood, SIR, Gossip, HopDistance, "
        f"PageRank and PushSum; got {type(protocol).__name__} — run it on "
        f"the single-device engine, or write its round body around "
        f"sharded.propagate"
    )


def topology_state(sg: ShardedGraph) -> dict:
    """The sharded graph's runtime-mutable leaves as a checkpointable
    pytree — the multi-chip mirror of sim/checkpoint.topology_state. Leaves
    keep their shardings, so ``sim.checkpoint.save_orbax`` writes each
    process's shards in parallel and a restore lands them back on the mesh.
    """
    ts = {
        "bkt_mask": sg.bkt_mask,
        "node_mask": sg.node_mask,
        "out_degree": sg.out_degree,
        "in_degree": sg.in_degree,
    }
    if sg.dyn_src is not None:
        ts["dyn_src"] = sg.dyn_src
        ts["dyn_dst"] = sg.dyn_dst
        ts["dyn_mask"] = sg.dyn_mask
    if sg.neighbors_mask is not None:
        ts["neighbors_mask"] = sg.neighbors_mask
    if sg.mxu_mask is not None:
        ts["mxu_mask"] = sg.mxu_mask
    if sg.diag_masks is not None:
        ts["diag_masks"] = sg.diag_masks
    return ts


def apply_topology_state(sg: ShardedGraph, ts: dict) -> ShardedGraph:
    """Re-apply a :func:`topology_state` onto a structurally-equal sharded
    graph (same shard count, capacity, and neighbor table presence)."""
    expected = set(topology_state(sg).keys())
    if expected != set(ts.keys()):
        raise ValueError(
            f"sharded topology state keys mismatch: checkpoint has "
            f"{sorted(ts.keys())}, graph expects {sorted(expected)} — shard "
            f"the same construction (capacity, neighbor table) it came from"
        )
    for name in expected:
        saved, cur = np.shape(ts[name]), tuple(getattr(sg, name).shape)
        if tuple(saved) != cur:
            raise ValueError(
                f"sharded topology state mismatch for {name!r}: saved shape "
                f"{tuple(saved)}, graph has {cur}"
            )
    # Place every restored leaf on the graph's mesh explicitly: a leaf that
    # came back host-side (npz) or committed to one device would otherwise
    # be rejected when mixed with sharded operands under shard_map.
    shard = NamedSharding(_mesh_of(sg), P(_mesh_of(sg).axis_names[0]))
    kw = {k: jax.device_put(jnp.asarray(v), shard) for k, v in ts.items()}
    return dataclasses.replace(sg, **kw)


# --------------------------------------------------------------- ring pass


def _ring_perm(S: int):
    """Send block to the next shard: after t applications, shard d holds the
    block originally on shard (d - t) mod S."""
    return [(i, (i + 1) % S) for i in range(S)]




def _ring_pass_unrolled(axis_name, S, rot, groups, diag, acc0, combine,
                        comm: _RingComm):
    """Unrolled ring rotation (used when diagonal pieces are present: each
    piece applies at a STATIC step with a STATIC shift, which a lax.scan
    body cannot express). S is small; the unroll is the same structure the
    single-chip hybrid uses for its diagonal stack. The halo hop is issued
    through the comm seam BEFORE the step's applies — transfer and
    shard-local compute both only read the resident block, so the hop is
    in flight across the whole step on overlap-capable backends."""
    pieces, masks, apply_diag = diag
    wants_step = bool(getattr(comm, "wants_step", False))
    acc = acc0
    for t in range(S):
        if wants_step and t < S - 1:
            comm.set_context(step=t)
        rot_next = comm.shift(rot) if t < S - 1 else rot
        for fn, *arrs in groups:
            acc = combine(acc, fn(rot, *(a[t] for a in arrs)))
        for pi, (tp, r) in enumerate(pieces):
            if tp == t:
                acc = combine(acc, apply_diag(rot, r, masks[pi]))
        rot = rot_next
    return acc


def _diag_or_piece(rot, r, mask):
    """out[j] |= rot[(j + r) % B] & mask[j] — a static circular shift."""
    return jnp.roll(rot, -r) & mask


def _diag_sum_piece(rot, r, mask):
    return jnp.roll(rot, -r) * mask


def _diag_max_piece(rot, r, mask):
    from p2pnetwork_tpu.ops.segment import neutral_min

    return jnp.where(mask, jnp.roll(rot, -r), neutral_min(rot.dtype))


def _diag_minplus_piece(rot, r, mask):
    return jnp.where(mask, jnp.roll(rot, -r) + 1.0, jnp.inf)


def _ring_pass(axis_name, S, frontier, groups, acc0, combine, diag=None,
               comm: Optional[_RingComm] = None):
    """One full ring rotation. ``groups`` is a sequence of ``(apply_fn,
    *arrays)`` bucket groups, every array carrying a leading ring-step axis
    ``[S, ...]`` — static (dst-sorted segment or MXU-blocked) and dynamic
    (unsorted) edges ride the same rotation; at step ``t`` each group's
    bucket ``t`` consumes the resident block, folding results with
    ``combine``.

    The halo hop rides the comm seam (``_RingComm``): it is ISSUED before
    the step's bucket applies — hop and applies both only read the
    resident block — so the transfer overlaps the shard-local compute on
    overlap-capable backends. When the static group is the MXU one-hot
    layout and the backend has a fused form (pallas), the hop and the
    bucket's blocked segment sum run as ONE kernel
    (ops/pallas_ring.ring_segment_sum): DMA started at grid step 0, the
    whole edge aggregation as the in-flight window, recv wait at the
    last grid step.

    The last bucket is peeled out of the scan: after it is applied there is
    nothing left to rotate, so running its hop would be one wasted ICI
    transfer per pass. Zero-width groups (unused dynamic capacity,
    absent MXU layout) are skipped at trace time.
    """
    comm = comm or _make_ring_comm(DEFAULT_COMM, axis_name, S)
    groups = [g for g in groups if g[1].shape[-1] > 0]
    if diag is not None and diag[0]:
        return _ring_pass_unrolled(axis_name, S, frontier, groups, diag,
                                   acc0, combine, comm)
    meta = []
    arrays = []
    for fn, *arrs in groups:
        meta.append((fn, len(arrs)))
        arrays += arrs

    def apply_all(acc, rot, xs, skip_first=False):
        i = 0
        for gi, (fn, n) in enumerate(meta):
            if not (skip_first and gi == 0):
                acc = combine(acc, fn(rot, *xs[i: i + n]))
            i += n
        return acc

    # The MXU static group's fused form (contrib gather, post-process,
    # exact flag, kernel block) — present only on the one-hot bucket
    # appliers (_bucket_*_mxu), consumed only by fusing backends.
    # `comm.fuses` (not a backend-name check) is the gate: a wrapping
    # comm (chaos/device.FaultyComm) carries its inner backend's name
    # but declines the fused form so the hop payload stays exposed.
    fused = getattr(meta[0][0], "fused", None) if meta else None
    use_fused = fused is not None and comm.fuses
    # graftquake seam: comms that key faults on the ring step ask for
    # the scan's step index via set_context; the bare backends
    # (wants_step=False) keep the exact pre-fault scan structure.
    wants_step = bool(getattr(comm, "wants_step", False))

    def ring_step(rc, xs):
        rot, acc = rc  # rot: frontier block resident this step
        if wants_step:
            bkt_arrays, t = xs
            comm.set_context(step=t)
        else:
            bkt_arrays = xs
        if use_fused:
            contrib_fn, post, exact, kblock = fused
            arrs0 = bkt_arrays[: meta[0][1]]
            rot_next, out = comm.fused_segment_sum(
                rot, contrib_fn(rot, *arrs0), arrs0[1], kblock, exact)
            acc = combine(acc, post(out))
            acc = apply_all(acc, rot, bkt_arrays, skip_first=True)
        else:
            rot_next = comm.shift(rot)
            acc = apply_all(acc, rot, bkt_arrays)
        return (rot_next, acc), None

    if S > 1:
        xs = tuple(a[: S - 1] for a in arrays)
        if wants_step:
            xs = (xs, jnp.arange(S - 1, dtype=jnp.int32))
        (rot, acc), _ = jax.lax.scan(ring_step, (frontier, acc0), xs)
    else:
        rot, acc = frontier, acc0
    return apply_all(acc, rot, tuple(a[S - 1] for a in arrays))


def _bucket_or(block, sorted_dst=True):
    def apply(rot, src, dst, m):
        contrib = (rot[src] & m).astype(jnp.int32)
        return jax.ops.segment_max(
            contrib, dst, num_segments=block, indices_are_sorted=sorted_dst
        ) > 0

    return apply


def _bucket_sum(block, sorted_dst=True):
    def apply(rot, src, dst, m):
        contrib = rot[src] * m
        return jax.ops.segment_sum(
            contrib, dst, num_segments=block, indices_are_sorted=sorted_dst
        )

    return apply


def _bucket_max(block, sorted_dst=True):
    def apply(rot, src, dst, m):
        from p2pnetwork_tpu.ops.segment import neutral_min

        contrib = jnp.where(m, rot[src], neutral_min(rot.dtype))
        return jax.ops.segment_max(
            contrib, dst, num_segments=block, indices_are_sorted=sorted_dst
        )

    return apply


def _bucket_minplus(block, sorted_dst=True):
    """Unit-hop min-plus bucket: ``out[v] = min(rot[u] + 1)`` over the
    bucket's live edges — the sharded ring layouts carry no weight
    channel, so every hop costs 1, exactly
    ops/segment.propagate_min_plus on an unweighted graph (and its
    ``DYNAMIC_LINK_COST`` for the dynamic region)."""

    def apply(rot, src, dst, m):
        contrib = jnp.where(m, rot[src] + 1.0, jnp.inf)
        return jax.ops.segment_min(
            contrib, dst, num_segments=block, indices_are_sorted=sorted_dst
        )

    return apply


def _bucket_or_mxu(block, mxu_block):
    """Bucket OR via the fused Pallas one-hot-matmul kernel
    (ops/pallas_edge.py — the one-hot never touches HBM); 0/1
    contributions are exact in the single-pass MXU mode."""
    from p2pnetwork_tpu.ops.pallas_edge import segment_sum_pallas_impl

    def contrib_of(rot, src, dst, m):
        return (rot[src] & m).astype(jnp.float32)

    def post(out):
        return out.reshape(-1)[:block] > 0

    def apply(rot, src, dst, m):  # [NB, W] each
        out = segment_sum_pallas_impl(contrib_of(rot, src, dst, m), dst,
                                      mxu_block, exact=False)
        return post(out)

    # Fused-ring form (comm="pallas"): same gather, same kernel math, the
    # halo DMA carried under the segment-sum grid (_ring_pass).
    apply.fused = (contrib_of, post, False, mxu_block)
    return apply


def _bucket_sum_mxu(block, mxu_block):
    from p2pnetwork_tpu.ops.pallas_edge import segment_sum_pallas_impl

    def contrib_of(rot, src, dst, m):
        return rot[src] * m  # 0/1 pressure: exact in single-pass mode

    def post(out):
        return out.reshape(-1)[:block]

    def apply(rot, src, dst, m):  # rot f32[B]; src/dst i32[NB, W]
        out = segment_sum_pallas_impl(contrib_of(rot, src, dst, m), dst,
                                      mxu_block, exact=False)
        return post(out)

    apply.fused = (contrib_of, post, False, mxu_block)
    return apply


def _groups_or(block, mxu_block, buckets, dyn_buckets, mxu_buckets):
    static = (
        (_bucket_or_mxu(block, mxu_block), *mxu_buckets)
        if mxu_buckets[0].shape[-1] > 0
        else (_bucket_or(block, sorted_dst=True), *buckets)
    )
    return [static, (_bucket_or(block, sorted_dst=False), *dyn_buckets)]


def _groups_sum(block, mxu_block, buckets, dyn_buckets, mxu_buckets):
    static = (
        (_bucket_sum_mxu(block, mxu_block), *mxu_buckets)
        if mxu_buckets[0].shape[-1] > 0
        else (_bucket_sum(block, sorted_dst=True), *buckets)
    )
    return [static, (_bucket_sum(block, sorted_dst=False), *dyn_buckets)]


# -------------------------------------------------------------------- flood


def _ring_rounds_or(axis_name, S, block, pieces, mxu_block, comm,
                    bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                    mxu_src, mxu_dst, mxu_mask, diag_masks,
                    node_mask, out_degree, seen0, frontier0, rounds):
    """Per-shard body (runs under shard_map): ``rounds`` flood rounds, each a
    full ring pass. All blocks carry a leading length-1 shard axis."""
    pass_ = _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    # Live-count denominator, like models/flood.py — under failures the
    # coverage must be of SURVIVORS, or dead-but-seen nodes push it past 1.
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )

    def one_round(carry, _):
        seen, frontier = carry  # [block] bool each
        delivered = pass_(frontier)
        new = delivered & ~seen & node_mask_b
        seen = seen | new
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        covered = jax.lax.psum(
            jnp.sum((seen & node_mask_b).astype(jnp.int32)), axis_name
        )
        return (seen, new), {"messages": msgs, "coverage": covered / n_live}

    (seen, frontier), stats = jax.lax.scan(
        one_round, (seen0[0], frontier0[0]), None, length=rounds
    )
    return seen[None], frontier[None], stats


@functools.lru_cache(maxsize=64)
def _flood_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
              pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    """Build (and cache) the compiled sharded flood program for this shape."""
    body = functools.partial(_ring_rounds_or, axis_name, S, block, pieces,
                             mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: the body may invoke the Pallas bucket kernel, whose
    # vma-typed lowering trips a cache bug in current JAX (see
    # ops/pallas_edge.py); scoped to the ring-body programs only.
    fn = shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh, check_vma=False,
        in_specs=(spec,) * 14,
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def _flood_seed(sg: ShardedGraph, source: int):
    S, block = sg.n_shards, sg.block
    seed = jnp.zeros((S, block), dtype=bool).at[
        source // block, source % block].set(True)
    return seed & sg.node_mask  # dead source seeds nothing (Flood.init parity)


def flood(sg: ShardedGraph, mesh: Mesh, source: int, rounds: int,
          axis_name: str = DEFAULT_AXIS, state0=None,
          return_state: bool = False, comm: str = DEFAULT_COMM):
    """Run ``rounds`` of single-source flood on the sharded graph.

    Returns ``(seen [S, block] bool, stats dict of [rounds] arrays)`` — the
    sharded equivalent of ``engine.run(graph, Flood(source), ...)``, and
    bit-identical to it (tests/test_sharded.py), including under runtime
    failures and connects.

    Resume path (the mesh-backed JaxSimNode's stepper): pass ``state0 =
    (seen, frontier)`` to continue a run (``source`` is then ignored) and
    ``return_state=True`` to get ``((seen, frontier), stats)`` back.
    """
    from p2pnetwork_tpu.models.flood import Flood

    S, block = sg.n_shards, sg.block
    if state0 is None:
        state0 = init_state(sg, Flood(source=source), None)
    seen0, frontier0 = state0
    fn = _flood_fn(mesh, axis_name, S, block, rounds, sg.diag_pieces,
                   sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    seen, frontier, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, seen0, frontier0,
    )
    if return_state:
        return (seen, frontier), stats
    return seen, stats


# --------------------------------------------------- flood-to-coverage


def _ring_coverage_or(axis_name, S, block, pieces, mxu_block, comm,
                      coverage_target,
                      max_rounds,
                      bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                      mxu_src, mxu_dst, mxu_mask, diag_masks,
                      node_mask, out_degree, seen0, frontier0,
                      ring0=None, ici_round=None, fault_round0=None):
    """Per-shard body: flood until the psum'd live coverage reaches the
    target — the device-side early-exit ``lax.while_loop`` of
    engine.run_until_coverage, multi-chip. The psum makes ``covered``
    identical on every shard, so the loop condition is replicated-consistent
    by construction. Messages accumulate in the two-limb counter
    (utils/accum.py) — multi-chip totals wrap int32 even sooner.

    ``ring0``/``ici_round`` (both or neither — the flight-recorder
    variant) append the per-round ring to the carry: every row is built
    from the psum'd replicated scalars, so the ring is replicated too
    and rides back as a fourth output. Results are bit-identical either
    way — the ring never feeds the loop's math.

    ``fault_round0`` (fault-spec comms only) is the GLOBAL round of this
    call's first round: the graftquake comm keys its fault sites on
    ``fault_round0 + r``, so a resumed/healed chunk hits exactly the
    sites an unchunked run would."""
    pass_ = _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks)
    wire_faults = (fault_round0 is not None
                   and getattr(pass_.comm, "wants_step", False))
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )
    rec = ring0 is not None

    def cond(carry):
        rounds, covered = carry[2], carry[3]
        return (covered / n_live < coverage_target) & (rounds < max_rounds)

    def body(carry):
        seen, frontier, rounds, prev_covered, hi, lo, occ = carry[:7]
        if wire_faults:
            pass_.comm.set_context(round=fault_round0 + rounds)
        delivered = pass_(frontier)
        new = delivered & ~seen & node_mask_b
        seen = seen | new
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        hi, lo = accum.add((hi, lo), msgs)
        covered = jax.lax.psum(jnp.sum((seen & node_mask_b).astype(jnp.int32)),
                               axis_name)
        # Per-round frontier occupancy, the engine's ints exactly
        # (ops/frontier.py occupancy: live-new count / live-node count as
        # f32) so the packed mean matches the single-chip summary
        # bit-for-bit — run-summary parity the mesh JaxSimNode tests pin.
        # `new` is disjoint from the prior seen and pre-masked, so its
        # live count IS the coverage delta — no extra psum per round.
        occ_delta = ((covered - prev_covered) / n_live).astype(jnp.float32)
        occ = occ + occ_delta
        out = (seen, new, rounds + 1, covered, hi, lo, occ)
        if not rec:
            return out
        return out + (flightrec.write_row(
            carry[7], rounds, occupancy=occ_delta, new=msgs,
            total=flightrec.total_f32(hi, lo), coverage=covered,
            active_lanes=1, ici_bytes=ici_round),)

    seen0_b = seen0[0]
    covered0 = jax.lax.psum(
        jnp.sum((seen0_b & node_mask_b).astype(jnp.int32)), axis_name
    )
    init = (seen0_b, frontier0[0], jnp.int32(0), covered0, *accum.zero(),
            jnp.float32(0.0))
    if rec:
        init = init + (ring0,)
    final = jax.lax.while_loop(cond, body, init)
    seen, frontier, rounds, covered, hi, lo, occ = final[:7]
    # One packed i32[5] (replicated) carries the whole summary back — the
    # engine's single-transfer trick; separate scalars each cost a
    # device->host round trip on tunneled backends. The fifth slot is the
    # mean per-round frontier occupancy (engine _stat_while parity).
    packed = accum.pack_summary(
        rounds, covered / n_live, (hi, lo),
        extra=occ / jnp.maximum(rounds, 1)
    )
    if rec:
        return seen[None], frontier[None], packed, final[7]
    return seen[None], frontier[None], packed


@functools.lru_cache(maxsize=64)
def _flood_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                  max_rounds: int, pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM, rec: bool = False):
    body = functools.partial(_ring_coverage_or, axis_name, S, block, pieces,
                             mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factory.
    # The recorder variant (rec=True) appends the replicated flight ring
    # and the static per-round ICI byte estimate to the arguments and the
    # ring to the outputs. A fault-spec comm (graftquake) appends one
    # more replicated scalar — the global round of the chunk's first
    # round — LAST, so string-comm programs keep their exact signature.
    faulty = not isinstance(comm, str)
    if faulty:
        wrapped = lambda target, *args: body(  # noqa: E731
            target, max_rounds, *args[:-1], fault_round0=args[-1])
    else:
        wrapped = lambda target, *args: body(target, max_rounds, *args)  # noqa: E731
    fn = shard_map(
        wrapped,
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 14 + ((P(), P()) if rec else ())
        + ((P(),) if faulty else ()),
        out_specs=(spec, spec, P()) + ((P(),) if rec else ()),
    )
    return jax.jit(fn)


#: Cached per-round ICI byte estimates for the flight recorder's
#: ``ici_bytes`` column, keyed on the compiled-shape config — the commviz
#: census is an abstract trace (tens of ms), not something to pay per
#: recorded run.
_REC_ICI_CACHE: dict = {}  # graftlint: ignore[unbounded-cache] -- keyed on compiled-shape config; one entry per distinct (ws, ba, shards) lowering, a finite vocabulary per process


def _rec_ici_round_bytes(key: tuple, build) -> int:
    """The per-round ICI byte estimate of one compiled loop config:
    ``commviz.ici_bytes_estimate`` of the loop fn (while-loop bodies are
    censused once = per round, ring passes scan-trip-weighted — the
    same pricing the bench multichip column publishes). ``build()``
    returns ``(fn, args, axis_size)``; the result is cached under
    ``key`` (shape-config identity — the estimate depends on block
    sizes and mesh width, not on graph contents)."""
    est = _REC_ICI_CACHE.get(key)
    if est is None:
        from p2pnetwork_tpu.parallel import commviz

        fn, args, axis_size = build()
        est = _REC_ICI_CACHE[key] = int(
            commviz.ici_bytes_estimate(fn, args, axis_size))
    return est


def _record_comm_faults(comm, rounds, S, *, round0: int = 0) -> None:
    """After a fault-spec run (graftquake): count the faults the executed
    round window actually hit into ``chaos_device_faults_total{kind}`` —
    a host replay of the schedule, exact by construction (the compiled
    loop carries no counter). No-op for backend-string comms, empty
    schedules, hop-free rings (S == 1) and zero-round runs."""
    if isinstance(comm, str) or S <= 1 or not rounds:
        return
    schedule = getattr(comm, "schedule", None)
    if schedule is None or not schedule.active:
        return
    from p2pnetwork_tpu.chaos import device as chaos_device

    chaos_device.record_faults(schedule, rounds=int(rounds),
                               n_steps=S - 1, n_shards=S,
                               round0=int(round0))


def flood_until_coverage(sg: ShardedGraph, mesh: Mesh, source: int, *,
                         coverage_target: float = 0.99,
                         max_rounds: int = 1024,
                         axis_name: str = DEFAULT_AXIS,
                         state0=None, return_state: bool = False,
                         adaptive_k: int = 0, comm: str = DEFAULT_COMM,
                         recorder=None, fault_round0: int = 0):
    """Flood until coverage of the LIVE population reaches the target —
    the north-star run-to-99% measurement (engine.run_until_coverage), on
    the multi-chip path. One XLA program, zero host round-trips per round.

    ``adaptive_k > 0`` (requires ``shard_graph(source_csr=True)``) runs
    rounds whose global frontier is small through the frontier-sparse
    path: the frontier rides as a replicated index list and each shard
    gathers only its edges from those senders, chunked into W-wide work
    items — O(k·W) work plus one tiny all-gather instead of the full ring
    pass. The budget is out-edge MASS (largest per-shard item count must
    fit ``adaptive_k``), so degree-skewed graphs get the win too: a hub
    costs ceil(row/W) items instead of widening every gather to its
    degree. Results are bit-identical to the dense loop (the multi-chip
    mirror of models/adaptive_flood.py).

    Returns ``(seen [S, block] bool, dict(rounds, coverage, messages))``
    with ``messages`` an exact Python int. Resume path (same contract as
    :func:`flood`): pass ``state0 = (seen, frontier)`` to continue a run
    (``source`` is then ignored) and ``return_state=True`` to get the full
    ``((seen, frontier), dict)`` back.

    ``recorder`` (a :class:`~p2pnetwork_tpu.sim.flightrec.FlightRecorder`,
    default off; dense loop only — the adaptive path refuses it) rides
    the per-round flight ring in the replicated carry and attaches
    ``out["flight_record"]``; the ``ici_bytes`` column carries this
    config's static per-round comm-census estimate (the same pricing the
    bench multichip column publishes, per backend). Results stay
    bit-identical to recorder-off runs on BOTH comm backends.

    ``comm`` also accepts a :class:`~p2pnetwork_tpu.chaos.device.FaultSpec`
    (graftquake): the ring runs on the spec's backend with its seeded
    fault schedule injected at the halo hops, keyed on the GLOBAL round
    ``fault_round0 + r`` (chunked/resumed drivers pass ``fault_round0``
    so every chunk hits the sites an unchunked run would); the faults the
    executed window hit are counted into
    ``chaos_device_faults_total{kind}`` after the run (dense loop only —
    the adaptive path refuses fault specs like it refuses the recorder).
    """
    from p2pnetwork_tpu.models.flood import Flood

    S, block = sg.n_shards, sg.block
    if state0 is None:
        state0 = init_state(sg, Flood(source=source), None)
    seen0, frontier0 = state0
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    common = (
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree,
    )
    ring = None
    if adaptive_k > 0:
        if recorder is not None:
            raise ValueError(
                "the flight recorder is not supported on the adaptive "
                "frontier-sparse path — record the dense loop "
                "(adaptive_k=0)")
        if not isinstance(_resolve_comm(comm), str):
            raise ValueError(
                "fault-spec comms are not supported on the adaptive "
                "frontier-sparse path — inject on the dense loop "
                "(adaptive_k=0)")
        if sg.csr_pos is None:
            raise ValueError(
                "adaptive_k requires a sender-CSR sharded graph — build "
                "with shard_graph(source_csr=True)"
            )
        fn = _flood_adaptive_cov_fn(
            mesh, axis_name, S, block, max_rounds, adaptive_k,
            max(sg.csr_span, 1), sg.diag_pieces, sg.mxu_block,
            _resolve_comm(comm),
        )
        seen, frontier, packed = fn(
            jnp.float32(coverage_target), *common,
            sg.csr_pos, sg.csr_offsets, seen0, frontier0,
        )
    else:
        resolved = _resolve_comm(comm)
        # Fault-spec comms (graftquake) take the global first-round
        # index as one extra trailing replicated scalar — traced, so
        # chunked drivers advance it without recompiling.
        ftail = () if isinstance(resolved, str) \
            else (jnp.int32(fault_round0),)
        if recorder is None:
            fn = _flood_cov_fn(mesh, axis_name, S, block, max_rounds,
                               sg.diag_pieces, sg.mxu_block, resolved)
            seen, frontier, packed = fn(
                jnp.float32(coverage_target), *common, seen0, frontier0,
                *ftail,
            )
        else:
            fn = _flood_cov_fn(mesh, axis_name, S, block, max_rounds,
                               sg.diag_pieces, sg.mxu_block, resolved,
                               rec=True)
            base_fn = _flood_cov_fn(mesh, axis_name, S, block, max_rounds,
                                    sg.diag_pieces, sg.mxu_block, resolved)
            ici = _rec_ici_round_bytes(
                ("flood", mesh, axis_name, S, block, resolved,
                 sg.diag_pieces, sg.mxu_block),
                lambda: (base_fn,
                         (jnp.float32(coverage_target), *common, seen0,
                          frontier0, *ftail), S))
            seen, frontier, packed, ring = fn(
                jnp.float32(coverage_target), *common, seen0, frontier0,
                recorder.init(), jnp.float32(ici), *ftail,
            )
            packed, ring = jax.device_get((packed, ring))
    out = accum.unpack_summary(packed)
    _record_comm_faults(comm, out["rounds"], S, round0=fault_round0)
    if ring is not None:
        out["flight_record"] = flightrec.trim(ring, out["rounds"])
    # The packed fifth slot is the mean per-round frontier occupancy —
    # surface it under the engine's summary key (run-summary parity:
    # engine.run_until_coverage on a flood returns the same dict).
    occ = out.pop("extra", None)
    if occ is not None:
        out["frontier_occupancy_mean"] = occ
    if return_state:
        return (seen, frontier), out
    return seen, out


# ------------------------------------------------------------------- gossip


def _ring_rounds_gossip(axis_name, S, block, rng, comm,
                        neighbors, neighbors_mask, node_mask,
                        values0, round_keys, alpha, rounds):
    """Per-shard body: ``rounds`` push-pull gossip rounds (models/gossip.py).

    Each node samples one incoming neighbor — the k-th VALID slot of its
    (liveness-re-masked) table row, matching the engine's draw — and pulls
    that neighbor's value over the ring: at ring step ``t`` the resident
    value block belongs to shard ``(my - t) mod S``, and each node whose
    partner lives there grabs its value — every node matches exactly one
    step, so the accumulated sum IS the pulled value. ``exact_rng=True``
    reproduces the engine's full-population draw bit-for-bit (verification
    mode, O(N) per shard).
    """
    nbrs = neighbors[0]  # [B, W] global ids
    nmask = neighbors_mask[0]
    nm = node_mask[0]
    my = jax.lax.axis_index(axis_name)
    count = jnp.sum(nmask, axis=1)
    has_neighbor = (count > 0) & nm
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(nm.astype(jnp.int32)), axis_name), 1
    )
    csum = jnp.cumsum(nmask, axis=1)
    comm_obj = _make_ring_comm(comm, axis_name, S)
    draw_u = _make_draw(
        axis_name, S, block, rng, my,
        sample=lambda k, shape: jax.random.randint(
            k, shape, 0, jnp.int32(2**31 - 1)
        ),
    )

    def one_round(values, rkey):
        key = jax.random.wrap_key_data(rkey)
        k = draw_u(key) % jnp.maximum(count, 1)
        slot = jnp.argmax((csum == (k + 1)[:, None]) & nmask, axis=1)
        partner = jnp.take_along_axis(nbrs, slot[:, None], axis=1)[:, 0]
        p_shard = partner // block
        p_local = partner % block

        # pcast: a fresh constant is shard-invariant by type; the ring
        # fold adds shard-varying blocks into it, so the accumulator must
        # be marked varying up front (scan carries demand matching vma).
        # jax 0.4.x (this image) has no vma typing at all — the constant
        # is already per-shard there, so the cast is an identity.
        acc0 = jnp.zeros((block,), values.dtype)
        if hasattr(jax.lax, "pcast"):
            acc0 = jax.lax.pcast(acc0, (axis_name,), to="varying")

        def ring_step(rc, t):
            rot, acc = rc
            # Halo hop issued first (comm seam): the pull below only READS
            # the resident block, so the transfer is in flight across it.
            rot_next = comm_obj.shift(rot)
            resident = (my - t) % S
            acc = acc + jnp.where(p_shard == resident, rot[p_local], 0.0)
            return (rot_next, acc), None

        if S > 1:
            (rot, pulled), _ = jax.lax.scan(
                ring_step, (values, acc0), jnp.arange(S - 1)
            )
        else:
            rot, pulled = values, acc0
        resident = (my - (S - 1)) % S
        pulled = pulled + jnp.where(p_shard == resident, rot[p_local], 0.0)

        mixed = (1.0 - alpha) * values + alpha * pulled
        values = jnp.where(has_neighbor, mixed, values)

        masked = values * nm
        mean = jax.lax.psum(jnp.sum(masked), axis_name) / n_live
        var = jax.lax.psum(
            jnp.sum(jnp.where(nm, (values - mean) ** 2, 0.0)), axis_name
        ) / n_live
        stats = {
            "messages": 2 * jax.lax.psum(
                jnp.sum(has_neighbor.astype(jnp.int32)), axis_name
            ),
            "variance": var,
            "mean": mean,
        }
        return values, stats

    values, stats = jax.lax.scan(one_round, values0[0], round_keys)
    return values[None], stats


@functools.lru_cache(maxsize=64)
def _gossip_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
               rng: str, comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_rounds_gossip, axis_name, S, block,
                             rng, comm)
    spec = P(axis_name)
    # check_vma=False under the pallas backend: see the ring-body factories.
    kw = {} if comm == "ppermute" else {"check_vma": False}
    fn = shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh,
        in_specs=(spec,) * 4 + (P(), P()),
        out_specs=(spec, P()),
        **kw,
    )
    return jax.jit(fn)


def gossip(sg: ShardedGraph, mesh: Mesh, protocol, key: jax.Array,
           rounds: int, axis_name: str = DEFAULT_AXIS,
           exact_rng: bool = False, rng: Optional[str] = None,
           values0=None, comm: str = DEFAULT_COMM):
    """Run ``rounds`` of push-pull gossip averaging (models/gossip.py) on
    the sharded graph — randomized consensus, the second protocol family
    reference users build on ``node_message`` [ref: README.md:20].

    Returns ``(values [S, block] f32, stats dict of [rounds] arrays)``. The
    init draw and per-round key schedule match ``engine.run``'s, so with
    ``exact_rng=True`` and ``S*block == n_pad`` the values are bit-identical
    to the single-device engine (tests/test_sharded.py).
    """
    if sg.neighbors is None:
        raise ValueError(
            "sharded gossip needs a partner table: shard a graph built "
            "with a neighbor table (from_edges build_neighbor_table=True)"
        )
    S, block = sg.n_shards, sg.block
    if values0 is None:
        values0 = init_state(sg, protocol, key)
    round_keys = jax.random.key_data(
        jax.random.split(jax.random.fold_in(key, 1), rounds)
    )
    fn = _gossip_fn(mesh, axis_name, S, block, rounds,
                    _resolve_rng(sg, exact_rng, rng), _resolve_comm(comm))
    values, stats = fn(
        sg.neighbors, sg.neighbors_mask, sg.node_mask, values0,
        round_keys, jnp.float32(protocol.alpha),
    )
    return values, stats


# ---------------------------------------------------------------------- SIR


#: Node tile size for the shard-count-invariant scalable RNG. One PRNG key
#: per 128-node tile, derived from the GLOBAL tile index — each shard only
#: generates its own tiles (O(block) work), and the draw stream does not
#: depend on how many shards the population is split across.
RNG_TILE = 128


def _make_draw(axis_name, S, block, rng, my, sample=None):
    """Per-shard random-draw function for the chosen RNG mode.

    - ``"exact"``: draw the full population on every shard, slice own block
      — O(N)/shard, bit-identical to the single-device engine (oracle mode).
    - ``"tile"`` (scalable default): one key per global 128-node tile —
      O(block)/shard AND invariant across shard counts, so results have a
      cross-shard-count regression oracle. Requires ``block % 128 == 0``
      (callers fall back to ``"fold"`` otherwise).
    - ``"fold"``: fold the shard index into the key — cheapest, but results
      change with the mesh size.

    ``sample(key, shape)`` defaults to a [0, 1) uniform draw.
    """
    if sample is None:
        sample = lambda k, shape: jax.random.uniform(k, shape)  # noqa: E731
    if rng == "tile" and block % RNG_TILE != 0:  # pragma: no cover
        raise ValueError("tile RNG requires block % 128 == 0")

    def draw(key):
        if rng == "exact":
            full = sample(key, (S * block,))
            return jax.lax.dynamic_slice(full, (my * block,), (block,))
        if rng == "tile":
            tiles = block // RNG_TILE
            base = my * tiles
            keys = jax.vmap(
                lambda i: jax.random.fold_in(key, base + i)
            )(jnp.arange(tiles))
            return jax.vmap(
                lambda k: sample(k, (RNG_TILE,))
            )(keys).reshape(block)
        return sample(jax.random.fold_in(key, my), (block,))

    return draw


def _resolve_rng(sg: ShardedGraph, exact_rng: bool, rng: Optional[str]) -> str:
    if exact_rng:
        return "exact"
    if rng is not None:
        if rng not in ("exact", "tile", "fold"):
            raise ValueError(
                f"rng must be 'exact', 'tile' or 'fold', got {rng!r}"
            )
        return rng
    return "tile" if sg.block % RNG_TILE == 0 else "fold"


def _make_sir_round(axis_name, S, block, rng, pieces, mxu_block, comm,
                    bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                    mxu_src, mxu_dst, mxu_mask, diag_masks,
                    node_mask, out_degree, one_minus_beta, gamma):
    """Build the per-shard SIR round closure (shared by the fixed-rounds
    scan and the run-to-coverage while_loop): ``one_round(status, key) ->
    (status, stats)`` with infection pressure via a ring sum pass.
    ``beta``/``gamma`` are replicated scalars (runtime operands, so a
    parameter sweep does not recompile per value); ``rng`` selects the
    uniform-draw scheme — see :func:`_make_draw`.
    """
    from p2pnetwork_tpu.models.sir import INFECTED, RECOVERED, SUSCEPTIBLE

    pass_ = _make_sum_pass(axis_name, S, block, pieces, mxu_block, comm,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    # Live-count denominator (models/sir.py parity under failures).
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )
    my = jax.lax.axis_index(axis_name)
    draw = _make_draw(axis_name, S, block, rng, my)

    def one_round(status, key):
        k_inf, k_rec = jax.random.split(key)
        infected = (status == INFECTED) & node_mask_b
        susceptible = (status == SUSCEPTIBLE) & node_mask_b

        pressure = pass_(infected.astype(jnp.float32))
        # one_minus_beta arrives precomputed in f64 then cast, matching the
        # engine's `jnp.power(1.0 - beta, ...)` constant bit-for-bit.
        p_infect = 1.0 - jnp.power(one_minus_beta, pressure)
        newly_infected = susceptible & (draw(k_inf) < p_infect)
        recovers = infected & (draw(k_rec) < gamma)

        status = jnp.where(newly_infected, INFECTED, status)
        status = jnp.where(recovers, RECOVERED, status)

        def frac(mask):
            return jax.lax.psum(jnp.sum(mask.astype(jnp.int32)), axis_name) / n_live

        stats = {
            "messages": jax.lax.psum(
                jnp.sum(jnp.where(infected, out_degree_b, 0)), axis_name
            ),
            "s_frac": frac((status == SUSCEPTIBLE) & node_mask_b),
            "i_frac": frac((status == INFECTED) & node_mask_b),
            "r_frac": frac((status == RECOVERED) & node_mask_b),
            "coverage": frac((status != SUSCEPTIBLE) & node_mask_b),
        }
        return status, stats

    return one_round


def _ring_rounds_sir(axis_name, S, block, rng, pieces, mxu_block, comm,
                     bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                     mxu_src, mxu_dst, mxu_mask, diag_masks,
                     node_mask, out_degree,
                     status0, round_keys, one_minus_beta, gamma, rounds):
    """Per-shard body: ``rounds`` SIR rounds (scan over replicated raw key
    data, engine.run key-schedule parity)."""
    one_round = _make_sir_round(
        axis_name, S, block, rng, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask,
        dyn_src, dyn_dst, dyn_mask, mxu_src, mxu_dst, mxu_mask, diag_masks,
        node_mask, out_degree, one_minus_beta, gamma,
    )

    def body(status, rkey):
        return one_round(status, jax.random.wrap_key_data(rkey))

    status, stats = jax.lax.scan(body, status0[0], round_keys)
    return status[None], stats


def _ring_coverage_sir(axis_name, S, block, rng, pieces, mxu_block, comm,
                       coverage_target, max_rounds,
                       bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                       mxu_src, mxu_dst, mxu_mask, diag_masks,
                       node_mask, out_degree,
                       status0, key_data, one_minus_beta, gamma):
    """Per-shard body: SIR until ever-infected coverage reaches the target
    (engine.run_until_coverage's key schedule: split the carried key each
    round). Messages accumulate in the two-limb counter."""
    one_round = _make_sir_round(
        axis_name, S, block, rng, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask,
        dyn_src, dyn_dst, dyn_mask, mxu_src, mxu_dst, mxu_mask, diag_masks,
        node_mask, out_degree, one_minus_beta, gamma,
    )

    def cond(carry):
        _, _, rounds, coverage, _, _ = carry
        return (coverage < coverage_target) & (rounds < max_rounds)

    def body(carry):
        status, kd, rounds, _, hi, lo = carry
        k, sub = jax.random.split(jax.random.wrap_key_data(kd))
        status, stats = one_round(status, sub)
        hi, lo = accum.add((hi, lo), stats["messages"])
        return (status, jax.random.key_data(k), rounds + 1,
                stats["coverage"], hi, lo)

    from p2pnetwork_tpu.models.sir import SUSCEPTIBLE

    node_mask_b = node_mask[0]
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )
    cov0 = jax.lax.psum(
        jnp.sum(((status0[0] != SUSCEPTIBLE) & node_mask_b).astype(jnp.int32)),
        axis_name,
    ) / n_live
    init = (status0[0], key_data, jnp.int32(0), cov0, *accum.zero())
    status, _, rounds, coverage, hi, lo = jax.lax.while_loop(cond, body, init)
    # Single-transfer summary, like the flood coverage body.
    return status[None], accum.pack_summary(rounds, coverage, (hi, lo))


@functools.lru_cache(maxsize=64)
def _sir_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                max_rounds: int, rng: str, pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_coverage_sir, axis_name, S, block, rng,
                             pieces, mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factory.
    fn = shard_map(
        lambda target, *args: body(target, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 13 + (P(), P(), P()),
        out_specs=(spec, P()),
    )
    return jax.jit(fn)


def sir_until_coverage(sg: ShardedGraph, mesh: Mesh, protocol,
                       key: jax.Array, *,
                       coverage_target: float = 0.99,
                       max_rounds: int = 1024,
                       axis_name: str = DEFAULT_AXIS,
                       exact_rng: bool = False, rng: Optional[str] = None,
                       status0=None, comm: str = DEFAULT_COMM):
    """Run SIR until the ever-infected coverage of the LIVE population
    reaches the target — engine.run_until_coverage's measurement for the
    epidemic protocol, on the multi-chip path. Same key schedule as the
    engine loop (split the carried key per round), so ``exact_rng=True``
    with ``S*block == n_pad`` is bit-identical to it.

    Returns ``(status [S, block] i32, dict(rounds, coverage, messages))``
    with ``messages`` an exact Python int.
    """
    S, block = sg.n_shards, sg.block
    if status0 is None:
        status0 = init_state(sg, protocol, key)
    fn = _sir_cov_fn(mesh, axis_name, S, block, max_rounds,
                     _resolve_rng(sg, exact_rng, rng), sg.diag_pieces,
                     sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    status, packed = fn(
        jnp.float32(coverage_target),
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, status0,
        jax.random.key_data(key),
        jnp.float32(1.0 - protocol.beta), jnp.float32(protocol.gamma),
    )
    return status, accum.unpack_summary(packed)


@functools.lru_cache(maxsize=64)
def _sir_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
            rng: str, pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_rounds_sir, axis_name, S, block, rng,
                             pieces, mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: the body may invoke the Pallas bucket kernel, whose
    # vma-typed lowering trips a cache bug in current JAX (see
    # ops/pallas_edge.py); scoped to the ring-body programs only.
    fn = shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh, check_vma=False,
        in_specs=(spec,) * 13 + (P(), P(), P()),
        out_specs=(spec, P()),
    )
    return jax.jit(fn)


def sir(sg: ShardedGraph, mesh: Mesh, protocol, key: jax.Array, rounds: int,
        axis_name: str = DEFAULT_AXIS, exact_rng: bool = False,
        rng: Optional[str] = None, status0=None,
        comm: str = DEFAULT_COMM):
    """Run ``rounds`` of SIR (models/sir.py) on the sharded graph.

    Returns ``(status [S, block] i32, stats dict of [rounds] arrays)``. The
    key schedule matches ``engine.run``'s, so with ``exact_rng=True`` and a
    node count divisible by the shard count this is bit-identical to the
    single-device engine (tests/test_sharded.py). The scalable default is
    ``rng="tile"`` — O(block) draws that are INVARIANT across shard counts
    (the same run on 1, 2, or 8 shards gives the same epidemic), falling
    back to ``"fold"`` when the block size is not tile-aligned.
    """
    S, block = sg.n_shards, sg.block
    if status0 is None:
        status0 = init_state(sg, protocol, key)
    # engine.run's schedule: one subkey per round off fold_in(key, 1).
    round_keys = jax.random.key_data(
        jax.random.split(jax.random.fold_in(key, 1), rounds)
    )
    fn = _sir_fn(mesh, axis_name, S, block, rounds,
                 _resolve_rng(sg, exact_rng, rng), sg.diag_pieces,
                 sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    status, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree,
        status0, round_keys,
        jnp.float32(1.0 - protocol.beta), jnp.float32(protocol.gamma),
    )
    return status, stats


# ------------------------------------------- generic value propagation


def _make_sum_pass(axis_name, S, block, pieces, mxu_block, comm,
                   bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                   mxu_src, mxu_dst, mxu_mask, diag_masks):
    """Build ``pass_(x) -> f32[block]``: one full ring rotation summing a
    per-node value over every incoming edge — the sharded mirror of
    ops/segment.propagate_sum. All bucket arrays arrive with their leading
    length-1 shard axis already peeled (``arr[0]``)."""
    groups = _groups_sum(
        block, mxu_block, (bkt_src[0], bkt_dst[0], bkt_mask[0]),
        (dyn_src[0], dyn_dst[0], dyn_mask[0]),
        (mxu_src[0], mxu_dst[0], mxu_mask[0]),
    )
    diag = (pieces, diag_masks[0], _diag_sum_piece)
    comm_obj = _make_ring_comm(comm, axis_name, S)

    def pass_(x):
        return _ring_pass(axis_name, S, x, groups,
                          jnp.zeros((block,), x.dtype), jnp.add, diag=diag,
                          comm=comm_obj)

    return pass_


def _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                  bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                  mxu_src, mxu_dst, mxu_mask, diag_masks):
    """Build ``pass_(frontier) -> bool[block]``: one ring rotation OR-ing a
    boolean signal over every incoming edge — the OR twin of
    :func:`_make_sum_pass`, shared by the flood bodies, the coverage
    loops, :func:`propagate` and the BFS hop-distance bodies."""
    groups = _groups_or(
        block, mxu_block, (bkt_src[0], bkt_dst[0], bkt_mask[0]),
        (dyn_src[0], dyn_dst[0], dyn_mask[0]),
        (mxu_src[0], mxu_dst[0], mxu_mask[0]),
    )
    diag = (pieces, diag_masks[0], _diag_or_piece)
    comm_obj = _make_ring_comm(comm, axis_name, S)

    def pass_(frontier):
        return _ring_pass(axis_name, S, frontier, groups,
                          jnp.zeros((block,), bool), jnp.logical_or,
                          diag=diag, comm=comm_obj)

    pass_.comm = comm_obj  # round-context handle for fault-wired loops
    return pass_


def _make_max_pass(axis_name, S, block, pieces, mxu_block, comm,
                   bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                   mxu_src, mxu_dst, mxu_mask, diag_masks):
    """Build ``pass_(x) -> x.dtype[block]``: one full ring rotation taking
    the per-node MAX over every incoming edge — segment buckets and
    diagonal shifts only (max cannot ride the one-hot-matmul MXU layout,
    which computes sums; :func:`propagate` rejects such graphs up front)."""
    from p2pnetwork_tpu.ops.segment import neutral_min

    groups = [
        (_bucket_max(block, sorted_dst=True),
         bkt_src[0], bkt_dst[0], bkt_mask[0]),
        (_bucket_max(block, sorted_dst=False),
         dyn_src[0], dyn_dst[0], dyn_mask[0]),
    ]
    diag = (pieces, diag_masks[0], _diag_max_piece)
    comm_obj = _make_ring_comm(comm, axis_name, S)

    def pass_(x):
        return _ring_pass(axis_name, S, x, groups,
                          jnp.full((block,), neutral_min(x.dtype), x.dtype),
                          jnp.maximum, diag=diag, comm=comm_obj)

    return pass_


def _make_minplus_pass(axis_name, S, block, pieces, mxu_block, comm,
                       bkt_src, bkt_dst, bkt_mask,
                       dyn_src, dyn_dst, dyn_mask,
                       mxu_src, mxu_dst, mxu_mask, diag_masks):
    """Build ``pass_(dist) -> f32[block]``: one full ring rotation taking
    the per-node MIN of ``dist[u] + 1`` over every incoming edge — one
    unit-weight Bellman-Ford round, the tropical-semiring sibling of
    :func:`_make_max_pass` (segment buckets only: min cannot ride the
    one-hot-matmul MXU layout, and the ring layouts carry no weight
    channel — ops/segment.propagate_min_plus's unweighted case)."""
    groups = [
        (_bucket_minplus(block, sorted_dst=True),
         bkt_src[0], bkt_dst[0], bkt_mask[0]),
        (_bucket_minplus(block, sorted_dst=False),
         dyn_src[0], dyn_dst[0], dyn_mask[0]),
    ]
    diag = (pieces, diag_masks[0], _diag_minplus_piece)
    comm_obj = _make_ring_comm(comm, axis_name, S)

    def pass_(x):
        return _ring_pass(axis_name, S, x, groups,
                          jnp.full((block,), jnp.inf, x.dtype),
                          jnp.minimum, diag=diag, comm=comm_obj)

    return pass_


def _propagate_body(axis_name, S, block, pieces, mxu_block, comm, op,
                    bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                    mxu_src, mxu_dst, mxu_mask, diag_masks,
                    node_mask, signal):
    node_mask_b = node_mask[0]
    if op == "or":
        pass_ = _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                              bkt_src, bkt_dst, bkt_mask,
                              dyn_src, dyn_dst, dyn_mask,
                              mxu_src, mxu_dst, mxu_mask, diag_masks)
        return (pass_(signal[0]) & node_mask_b)[None]
    if op == "max":
        from p2pnetwork_tpu.ops.segment import neutral_min

        pass_ = _make_max_pass(axis_name, S, block, pieces, mxu_block, comm,
                               bkt_src, bkt_dst, bkt_mask,
                               dyn_src, dyn_dst, dyn_mask,
                               mxu_src, mxu_dst, mxu_mask, diag_masks)
        out = pass_(signal[0])
        return jnp.where(node_mask_b, out, neutral_min(out.dtype))[None]
    if op == "minplus":
        pass_ = _make_minplus_pass(axis_name, S, block, pieces, mxu_block,
                                   comm, bkt_src, bkt_dst, bkt_mask,
                                   dyn_src, dyn_dst, dyn_mask,
                                   mxu_src, mxu_dst, mxu_mask, diag_masks)
        out = pass_(signal[0])
        return jnp.where(node_mask_b, out, jnp.inf)[None]
    pass_ = _make_sum_pass(axis_name, S, block, pieces, mxu_block, comm,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks)
    out = pass_(signal[0])
    return (out * node_mask_b.astype(out.dtype))[None]


@functools.lru_cache(maxsize=64)
def _propagate_fn(mesh: Mesh, axis_name: str, S: int, block: int, op: str,
                  pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_propagate_body, axis_name, S, block, pieces,
                             mxu_block, comm, op)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(body, mesh=mesh, check_vma=False,
                       in_specs=(spec,) * 12, out_specs=spec)
    return jax.jit(fn)


def propagate(sg: ShardedGraph, mesh: Mesh, signal: jax.Array,
              op: str = "sum", axis_name: str = DEFAULT_AXIS,
              comm: str = DEFAULT_COMM) -> jax.Array:
    """One aggregation pass over every edge of the sharded graph: the
    multi-chip mirror of ``ops.segment.propagate_or`` / ``propagate_sum``,
    and the extension seam for protocols the library does not ship — the
    reference's users write their own protocol logic [ref: README.md:20];
    here they write a per-round function of elementwise updates around this
    call and it runs at ring-sharded scale.

    ``signal`` is ``[S, block]`` (bool for ``op="or"``, float for
    ``op="sum"``, float/int for ``op="max"``, f32 distances for
    ``op="minplus"``); returns the per-node aggregate in the same layout,
    masked to live nodes (``max`` masks to the dtype's -inf/int-min
    identity, ``minplus`` to ``+inf``). Static + dynamic
    (runtime-connected) edges and the ring-decomposed diagonals all
    contribute, exactly as in the shipped protocol bodies. ``op="max"``
    and ``op="minplus"`` need the segment layout: shard the graph
    without the MXU remainder (no ``hybrid=True``/``min_count``) —
    one-hot matmuls compute sums, not maxima/minima. ``minplus`` is one
    unit-weight Bellman-Ford round — the ring layouts carry no weight
    channel, so it matches ``ops.segment.propagate_min_plus`` on
    UNWEIGHTED graphs (weighted routing rides the GSPMD auto path).
    ``comm`` selects the halo-exchange backend (:data:`COMM_BACKENDS`).
    """
    if op not in ("or", "sum", "max", "minplus"):
        raise ValueError(
            f"op must be 'or', 'sum', 'max' or 'minplus', got {op!r}")
    if op in ("max", "minplus") and sg.mxu_src is not None:
        raise ValueError(
            f"op={op!r} cannot ride the MXU one-hot layout — shard_graph "
            "without hybrid/min_count for max/min-aggregating protocols"
        )
    fn = _propagate_fn(mesh, axis_name, sg.n_shards, sg.block, op,
                       sg.diag_pieces, sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    return fn(sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
              dyn_src, dyn_dst, dyn_mask, mxu_src, mxu_dst, mxu_mask,
              _diag_masks_or_empty(sg), sg.node_mask, signal)


# ---------------------------------------------------- pagerank / pushsum


def _make_pagerank_round(axis_name, S, block, pieces, mxu_block, comm,
                         bkt_src, bkt_dst, bkt_mask,
                         dyn_src, dyn_dst, dyn_mask,
                         mxu_src, mxu_dst, mxu_mask, diag_masks,
                         node_mask, out_degree, damping, one_minus_damping):
    """Build the per-shard power-iteration round closure
    (models/pagerank.py arithmetic, edge sums over the ring), shared by
    the fixed-rounds scan and the run-to-residual while_loop. ``damping``
    rides as a replicated runtime operand so a damping sweep does not
    recompile; ``one_minus_damping`` arrives precomputed in f64 then cast,
    matching the engine's constant folding."""
    pass_ = _make_sum_pass(axis_name, S, block, pieces, mxu_block, comm,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b = node_mask[0]
    mask_f = node_mask_b.astype(jnp.float32)
    deg = out_degree[0]
    deg_f = deg.astype(jnp.float32)
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    ).astype(jnp.float32)
    msgs = jax.lax.psum(
        jnp.sum(jnp.where(node_mask_b, deg, 0)), axis_name
    )

    def one_round(ranks):
        contrib = jnp.where(node_mask_b & (deg > 0),
                            ranks / jnp.maximum(deg_f, 1.0), 0.0)
        pulled = pass_(contrib)
        dangling = jax.lax.psum(
            jnp.sum(jnp.where(node_mask_b & (deg == 0), ranks, 0.0)),
            axis_name,
        )
        new = (one_minus_damping / n_live
               + damping * (pulled + dangling / n_live)) * mask_f
        stats = {
            "messages": msgs,
            "residual": jax.lax.psum(jnp.sum(jnp.abs(new - ranks)), axis_name),
            "rank_total": jax.lax.psum(jnp.sum(new), axis_name),
            "rank_max": jax.lax.pmax(jnp.max(new), axis_name),
        }
        return new, stats

    return one_round


def _ring_rounds_pagerank(axis_name, S, block, pieces, mxu_block, comm,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks,
                          node_mask, out_degree,
                          ranks0, damping, one_minus_damping, rounds):
    """Per-shard body: ``rounds`` damped power-iteration rounds."""
    one_round = _make_pagerank_round(
        axis_name, S, block, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, diag_masks,
        node_mask, out_degree, damping, one_minus_damping,
    )
    ranks, stats = jax.lax.scan(lambda r, _: one_round(r), ranks0[0], None,
                                length=rounds)
    return ranks[None], stats


@functools.lru_cache(maxsize=64)
def _pagerank_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
                 pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_rounds_pagerank, axis_name, S, block,
                             pieces, mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh, check_vma=False,
        in_specs=(spec,) * 13 + (P(), P()),
        out_specs=(spec, P()),
    )
    return jax.jit(fn)


def pagerank(sg: ShardedGraph, mesh: Mesh, protocol, rounds: int,
             axis_name: str = DEFAULT_AXIS, ranks0=None,
             comm: str = DEFAULT_COMM):
    """Run ``rounds`` of PageRank power iteration (models/pagerank.py) on
    the sharded graph. Deterministic — no RNG. Returns ``(ranks [S, block]
    f32, stats dict of [rounds] arrays)``; agrees with the single-device
    engine to f32 summation-order tolerance (edge sums accumulate in
    bucket/ring order here, receiver order there)."""
    S, block = sg.n_shards, sg.block
    if ranks0 is None:
        ranks0 = init_state(sg, protocol, None)
    fn = _pagerank_fn(mesh, axis_name, S, block, rounds, sg.diag_pieces,
                      sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    return fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, ranks0,
        jnp.float32(protocol.damping), jnp.float32(1.0 - protocol.damping),
    )


def _freeze_while(state0, value0, one_step, keep_going,
                 steps_per_round: int):
    """The shared device-side early-exit loop for the ring's run-to-*
    measurements, with optional T-batched iterations.

    ``one_step(state) -> (state, value, messages)`` is one protocol
    round; the loop runs while ``keep_going(value, rounds)`` holds,
    accumulating messages in the two-limb counter. ``steps_per_round=T``
    batches T rounds per while iteration as a ``lax.scan``, each
    sub-step re-checking the predicate and freezing the WHOLE carry once
    it fails — bit-exact vs T=1 by construction (the engine's
    ``_stat_while`` contract; rounds-bound runs amortize the
    per-iteration dispatch/collective floor T-fold). The freeze masks
    every state leaf; a leaf whose post-exit value is semantically dead
    (e.g. the walker's chained key data) freezes harmlessly, because a
    frozen sub-step implies the next ``cond`` is False.

    Returns ``(state, rounds, value, (hi, lo))`` — callers pack their
    own summaries.
    """

    def cond(carry):
        _, rounds, value, _, _ = carry
        return keep_going(value, rounds)

    def body(carry):
        state, rounds, _, hi, lo = carry
        state, value, msgs = one_step(state)
        hi, lo = accum.add((hi, lo), msgs)
        return (state, rounds + 1, value, hi, lo)

    def batched_body(carry):
        def substep(c, _):
            state, rounds, value, hi, lo = c
            live = keep_going(value, rounds)
            nstate, nvalue, msgs = one_step(state)
            state = jax.tree_util.tree_map(
                lambda new, old: jnp.where(live, new, old), nstate, state)
            hi, lo = accum.add(
                (hi, lo), jnp.where(live, msgs, jnp.zeros_like(msgs)))
            rounds = jnp.where(live, rounds + 1, rounds)
            value = jnp.where(live, nvalue, value)
            return (state, rounds, value, hi, lo), None

        carry, _ = jax.lax.scan(substep, carry, None,
                                length=steps_per_round)
        return carry

    init = (state0, jnp.int32(0), value0, *accum.zero())
    state, rounds, value, hi, lo = jax.lax.while_loop(
        cond, body if steps_per_round == 1 else batched_body, init)
    return state, rounds, value, (hi, lo)


def _ring_residual_pagerank(axis_name, S, block, pieces, mxu_block, comm,
                            steps_per_round, tol, max_rounds,
                            bkt_src, bkt_dst, bkt_mask,
                            dyn_src, dyn_dst, dyn_mask,
                            mxu_src, mxu_dst, mxu_mask, diag_masks,
                            node_mask, out_degree,
                            ranks0, damping, one_minus_damping):
    """Per-shard body: power iteration until the L1 residual drops below
    ``tol`` — engine.run_until_converged's measurement on the multi-chip
    path, with the packed single-transfer summary. ``steps_per_round``
    batches iterations per while step (bit-exact vs 1; _freeze_while)."""
    one_round = _make_pagerank_round(
        axis_name, S, block, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, diag_masks,
        node_mask, out_degree, damping, one_minus_damping,
    )

    def one_step(ranks):
        ranks, stats = one_round(ranks)
        return ranks, stats["residual"], stats["messages"]

    ranks, rounds, residual, (hi, lo) = _freeze_while(
        ranks0[0], jnp.float32(jnp.inf), one_step,
        lambda v, r: (v >= tol) & (r < max_rounds), steps_per_round)
    return ranks[None], accum.pack_summary(rounds, residual, (hi, lo))


@functools.lru_cache(maxsize=64)
def _pagerank_residual_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                          max_rounds: int, pieces=(), mxu_block: int = 128,
                          steps_per_round: int = 1,
                          comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_residual_pagerank, axis_name, S, block,
                             pieces, mxu_block, comm, steps_per_round)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda tol, *args: body(tol, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 13 + (P(), P()),
        out_specs=(spec, P()),
    )
    return jax.jit(fn)


def pagerank_until_residual(sg: ShardedGraph, mesh: Mesh, protocol, *,
                            tol: float = 1e-6, max_rounds: int = 1024,
                            steps_per_round: int = 1,
                            axis_name: str = DEFAULT_AXIS, ranks0=None,
                            comm: str = DEFAULT_COMM):
    """Run PageRank until the L1 residual drops below ``tol`` — the
    convergence measurement (engine.run_until_converged with
    stat="residual"), multi-chip, as one device-side while_loop. Returns
    ``(ranks [S, block] f32, dict(rounds, value, messages))`` with
    ``value`` the final residual and ``messages`` an exact Python int."""
    S, block = sg.n_shards, sg.block
    if steps_per_round < 1:
        raise ValueError(
            f"steps_per_round must be >= 1, got {steps_per_round}")
    if ranks0 is None:
        ranks0 = init_state(sg, protocol, None)
    fn = _pagerank_residual_fn(mesh, axis_name, S, block, max_rounds,
                               sg.diag_pieces, sg.mxu_block,
                               int(steps_per_round), _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    ranks, packed = fn(
        jnp.float32(tol),
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, ranks0,
        jnp.float32(protocol.damping), jnp.float32(1.0 - protocol.damping),
    )
    out = accum.unpack_summary(packed)
    out["value"] = out.pop("coverage")
    return ranks, out


def _ring_leader_quiet(axis_name, S, block, pieces, mxu_block, comm,
                       max_rounds,
                       bkt_src, bkt_dst, bkt_mask,
                       dyn_src, dyn_dst, dyn_mask,
                       mxu_src, mxu_dst, mxu_mask, diag_masks,
                       node_mask, out_degree):
    """Per-shard body: highest-live-id leader election run to quiescence —
    the multi-chip mirror of models/leader.py under
    engine.run_until_converged(stat="changed", threshold=1), as one
    device-side while_loop. Nodes re-broadcast only the round after they
    learned a better candidate; the loop exits on the first all-quiet
    round (which is executed and message-counted, matching the engine)."""
    from p2pnetwork_tpu.ops.segment import neutral_min

    pass_ = _make_max_pass(axis_name, S, block, pieces, mxu_block, comm,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b, deg = node_mask[0], out_degree[0]
    neutral = neutral_min(jnp.int32)
    my = jax.lax.axis_index(axis_name)
    ids = (my * block + jnp.arange(block)).astype(jnp.int32)
    known0 = jnp.where(node_mask_b, ids, -1)
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )

    def cond(carry):
        _, _, rounds, changed, _, _ = carry
        return (changed > 0) & (rounds < max_rounds)

    def body(carry):
        known, frontier, rounds, _, hi, lo = carry
        msgs = jax.lax.psum(jnp.sum(jnp.where(frontier, deg, 0)), axis_name)
        heard = pass_(jnp.where(frontier, known, neutral))
        new_known = jnp.where(node_mask_b, jnp.maximum(known, heard), -1)
        changed_mask = (new_known != known) & node_mask_b
        changed = jax.lax.psum(
            jnp.sum(changed_mask.astype(jnp.int32)), axis_name
        )
        hi, lo = accum.add((hi, lo), msgs)
        return new_known, changed_mask, rounds + 1, changed, hi, lo

    init = (known0, node_mask_b, jnp.int32(0),
            jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name),
            *accum.zero())
    known, _, rounds, _, hi, lo = jax.lax.while_loop(cond, body, init)
    winner = jax.lax.pmax(jnp.max(known), axis_name)
    agreed = jax.lax.psum(
        jnp.sum(((known == winner) & node_mask_b).astype(jnp.int32)),
        axis_name,
    )
    return known[None], accum.pack_summary(rounds, agreed / n_live, (hi, lo))


@functools.lru_cache(maxsize=64)
def _leader_quiet_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                     max_rounds: int, pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_leader_quiet, axis_name, S, block,
                             pieces, mxu_block, comm, max_rounds)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(body, mesh=mesh, check_vma=False,
                       in_specs=(spec,) * 12, out_specs=(spec, P()))
    return jax.jit(fn)


def leader_until_quiet(sg: ShardedGraph, mesh: Mesh, *,
                       max_rounds: int = 1024,
                       axis_name: str = DEFAULT_AXIS,
                       comm: str = DEFAULT_COMM):
    """Highest-live-id leader election run until no node learns anything —
    the multi-chip convergence loop of models/leader.py. Returns
    ``(known [S, block] i32, dict(rounds, coverage, messages))`` where
    ``coverage`` is the fraction of live nodes agreeing on the global
    winner (1.0 on a connected live graph) and ``messages`` an exact
    Python int. Requires the segment layout (``op="max"`` constraint —
    shard_graph without hybrid/min_count)."""
    if sg.mxu_src is not None:
        raise ValueError(
            "leader_until_quiet cannot ride the MXU one-hot layout — "
            "shard_graph without hybrid/min_count for max aggregation"
        )
    S, block = sg.n_shards, sg.block
    fn = _leader_quiet_fn(mesh, axis_name, S, block, max_rounds,
                          sg.diag_pieces, sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    known, packed = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree,
    )
    return known, accum.unpack_summary(packed)


def _make_pushsum_round(axis_name, S, block, pieces, mxu_block, comm,
                        bkt_src, bkt_dst, bkt_mask,
                        dyn_src, dyn_dst, dyn_mask,
                        mxu_src, mxu_dst, mxu_mask, diag_masks,
                        node_mask, out_degree):
    """Build the per-shard push-sum round closure (models/pushsum.py
    arithmetic — mass split over out-edges, two ring sums per round),
    shared by the fixed-rounds scan and the run-to-variance while_loop."""
    pass_ = _make_sum_pass(axis_name, S, block, pieces, mxu_block, comm,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b = node_mask[0]
    mask_f = node_mask_b.astype(jnp.float32)
    deg = out_degree[0]
    shares = 1.0 / (deg.astype(jnp.float32) + 1.0)
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )
    msgs = jax.lax.psum(
        jnp.sum(jnp.where(node_mask_b, deg, 0)), axis_name
    )

    def one_round(s, w):
        s_share = s * shares
        w_share = w * shares
        s = (s_share + pass_(s_share)) * mask_f
        w = (w_share + pass_(w_share)) * mask_f
        est = jnp.where(w > 0, s / jnp.maximum(w, 1e-30), 0.0)
        mean = jax.lax.psum(jnp.sum(est * mask_f), axis_name) / n_live
        var = jax.lax.psum(
            jnp.sum(jnp.where(node_mask_b, (est - mean) ** 2, 0.0)), axis_name
        ) / n_live
        stats = {
            "messages": msgs,
            "s_total": jax.lax.psum(jnp.sum(s), axis_name),
            "w_total": jax.lax.psum(jnp.sum(w), axis_name),
            "variance": var,
            "mean": mean,
        }
        return s, w, stats

    return one_round


def _ring_rounds_pushsum(axis_name, S, block, pieces, mxu_block, comm,
                         bkt_src, bkt_dst, bkt_mask,
                         dyn_src, dyn_dst, dyn_mask,
                         mxu_src, mxu_dst, mxu_mask, diag_masks,
                         node_mask, out_degree, s0, w0, rounds):
    """Per-shard body: ``rounds`` push-sum rounds."""
    one_round = _make_pushsum_round(
        axis_name, S, block, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, diag_masks, node_mask, out_degree,
    )

    def body(carry, _):
        s, w, stats = one_round(*carry)
        return (s, w), stats

    (s, w), stats = jax.lax.scan(body, (s0[0], w0[0]), None, length=rounds)
    return s[None], w[None], stats


def _ring_variance_pushsum(axis_name, S, block, pieces, mxu_block, comm,
                           steps_per_round, tol, max_rounds,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks,
                           node_mask, out_degree, s0, w0):
    """Per-shard body: push-sum until the estimate variance drops below
    ``tol`` — engine.run_until_converged's measurement on the multi-chip
    path, with the packed single-transfer summary. ``steps_per_round``
    batches rounds per while step (bit-exact vs 1; _freeze_while —
    push-sum's ring rounds are deterministic, no key chain)."""
    one_round = _make_pushsum_round(
        axis_name, S, block, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, diag_masks, node_mask, out_degree,
    )

    def one_step(state):
        s, w = state
        s, w, stats = one_round(s, w)
        return (s, w), stats["variance"], stats["messages"]

    (s, w), rounds, var, (hi, lo) = _freeze_while(
        (s0[0], w0[0]), jnp.float32(jnp.inf), one_step,
        lambda v, r: (v >= tol) & (r < max_rounds), steps_per_round)
    return s[None], w[None], accum.pack_summary(rounds, var, (hi, lo))


@functools.lru_cache(maxsize=64)
def _pushsum_variance_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                         max_rounds: int, pieces=(), mxu_block: int = 128,
                         steps_per_round: int = 1,
                         comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_variance_pushsum, axis_name, S, block,
                             pieces, mxu_block, comm, steps_per_round)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda tol, *args: body(tol, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 14,
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def pushsum_until_variance(sg: ShardedGraph, mesh: Mesh, protocol,
                           key: jax.Array, *,
                           tol: float = 1e-9, max_rounds: int = 1024,
                           steps_per_round: int = 1,
                           axis_name: str = DEFAULT_AXIS, state0=None,
                           comm: str = DEFAULT_COMM):
    """Run push-sum until the estimate variance drops below ``tol`` — the
    consensus-reached measurement (engine.run_until_converged with
    stat="variance"), multi-chip. Returns ``((s, w), dict(rounds, value,
    messages))`` with ``value`` the final variance. ``steps_per_round``
    batches rounds per while iteration (bit-exact vs 1 — the same freeze
    contract as the engine loops)."""
    S, block = sg.n_shards, sg.block
    if steps_per_round < 1:
        raise ValueError(
            f"steps_per_round must be >= 1, got {steps_per_round}")
    if state0 is None:
        state0 = init_state(sg, protocol, key)
    s0, w0 = state0
    fn = _pushsum_variance_fn(mesh, axis_name, S, block, max_rounds,
                              sg.diag_pieces, sg.mxu_block,
                              int(steps_per_round), _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    s, w, packed = fn(
        jnp.float32(tol),
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, s0, w0,
    )
    out = accum.unpack_summary(packed)
    out["value"] = out.pop("coverage")
    return (s, w), out


@functools.lru_cache(maxsize=64)
def _pushsum_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
                pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_rounds_pushsum, axis_name, S, block,
                             pieces, mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh, check_vma=False,
        in_specs=(spec,) * 14,
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def pushsum(sg: ShardedGraph, mesh: Mesh, protocol, key: jax.Array,
            rounds: int, axis_name: str = DEFAULT_AXIS, state0=None,
            comm: str = DEFAULT_COMM):
    """Run ``rounds`` of push-sum consensus (models/pushsum.py) on the
    sharded graph. ``key`` seeds the initial values exactly as the engine
    path does (Gossip-init parity); pass ``state0 = (s, w)`` to continue a
    run instead. Returns ``((s, w) [S, block] f32 each, stats dict)``;
    the conservation invariants (sum(s) fixed, sum(w) == live count) hold
    here too, to f32 summation order."""
    S, block = sg.n_shards, sg.block
    if state0 is None:
        state0 = init_state(sg, protocol, key)
    s0, w0 = state0
    fn = _pushsum_fn(mesh, axis_name, S, block, rounds, sg.diag_pieces,
                     sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    s, w, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, s0, w0,
    )
    return (s, w), stats


# ------------------------------------------------------------ hop distance


def _make_hopdist_round(axis_name, S, block, pieces, mxu_block, comm,
                        bkt_src, bkt_dst, bkt_mask,
                        dyn_src, dyn_dst, dyn_mask,
                        mxu_src, mxu_dst, mxu_mask, diag_masks,
                        node_mask, out_degree):
    """Per-shard BFS round closure (models/hopdist.py arithmetic): the wave
    is the flood wave; nodes record the first round that reaches them."""
    pass_ = _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )

    def one_round(dist, frontier, rnd):
        delivered = pass_(frontier)
        new = delivered & (dist < 0) & node_mask_b
        rnd = rnd + 1
        dist = jnp.where(new, rnd, dist)
        reached = (dist >= 0) & node_mask_b
        stats = {
            "messages": jax.lax.psum(
                jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
            ),
            "coverage": jax.lax.psum(
                jnp.sum(reached.astype(jnp.int32)), axis_name
            ) / n_live,
            "frontier": jax.lax.psum(jnp.sum(new.astype(jnp.int32)),
                                     axis_name),
            "max_dist": jax.lax.pmax(jnp.max(dist), axis_name),
        }
        return dist, new, rnd, stats

    return one_round


def _ring_rounds_hopdist(axis_name, S, block, pieces, mxu_block, comm,
                         bkt_src, bkt_dst, bkt_mask,
                         dyn_src, dyn_dst, dyn_mask,
                         mxu_src, mxu_dst, mxu_mask, diag_masks,
                         node_mask, out_degree,
                         dist0, frontier0, round0, rounds):
    one_round = _make_hopdist_round(
        axis_name, S, block, pieces, mxu_block, comm,
        bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, diag_masks, node_mask, out_degree,
    )

    def body(carry, _):
        dist, frontier, rnd = carry
        dist, frontier, rnd, stats = one_round(dist, frontier, rnd)
        return (dist, frontier, rnd), stats

    (dist, frontier, rnd), stats = jax.lax.scan(
        body, (dist0[0], frontier0[0], round0), None, length=rounds
    )
    return dist[None], frontier[None], rnd, stats


@functools.lru_cache(maxsize=64)
def _hopdist_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
                pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_rounds_hopdist, axis_name, S, block,
                             pieces, mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh, check_vma=False,
        in_specs=(spec,) * 14 + (P(),),
        out_specs=(spec, spec, P(), P()),
    )
    return jax.jit(fn)


def hopdist(sg: ShardedGraph, mesh: Mesh, protocol, rounds: int,
            axis_name: str = DEFAULT_AXIS, state0=None,
            comm: str = DEFAULT_COMM):
    """Run ``rounds`` of BFS hop-distance (models/hopdist.py) on the sharded
    graph. Deterministic; integer state, so parity with the single-device
    engine is bit-exact. Returns ``((dist, frontier, round), stats)`` with
    ``dist [S, block] i32`` (-1 = unreached)."""
    S, block = sg.n_shards, sg.block
    if state0 is None:
        state0 = init_state(sg, protocol, None)
    dist0, frontier0, round0 = state0
    fn = _hopdist_fn(mesh, axis_name, S, block, rounds, sg.diag_pieces,
                     sg.mxu_block, _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    dist, frontier, rnd, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
        mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, dist0, frontier0, round0,
    )
    return (dist, frontier, rnd), stats


def _ring_coverage_hopdist(axis_name, S, block, pieces, mxu_block, comm,
                           coverage_target, max_rounds,
                           bkt_src, bkt_dst, bkt_mask,
                           dyn_src, dyn_dst, dyn_mask,
                           mxu_src, mxu_dst, mxu_mask, diag_masks,
                           node_mask, out_degree, dist0, frontier0, round0):
    """Per-shard body: BFS until coverage reaches the target OR the wave
    dies out (frontier empty) — whichever first — as one while_loop with
    the packed single-transfer summary. Lean: only the collectives the
    loop consumes (messages, live frontier count, covered count) run per
    round; eccentricity is a single reduction after the loop."""
    pass_ = _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )

    def cond(carry):
        _, _, rnd, alive, covered, _, _ = carry
        return ((alive > 0) & (rnd - round0 < max_rounds)
                & (covered / n_live < coverage_target))

    def body(carry):
        dist, frontier, rnd, _, covered, hi, lo = carry
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        hi, lo = accum.add((hi, lo), msgs)
        delivered = pass_(frontier)
        new = delivered & (dist < 0) & node_mask_b
        rnd = rnd + 1
        dist = jnp.where(new, rnd, dist)
        alive = jax.lax.psum(jnp.sum(new.astype(jnp.int32)), axis_name)
        return dist, new, rnd, alive, covered + alive, hi, lo

    covered0 = jax.lax.psum(
        jnp.sum(((dist0[0] >= 0) & node_mask_b).astype(jnp.int32)), axis_name
    )
    alive0 = jax.lax.psum(jnp.sum(frontier0[0].astype(jnp.int32)), axis_name)
    init = (dist0[0], frontier0[0], round0, alive0, covered0, *accum.zero())
    dist, frontier, rnd, _, covered, hi, lo = jax.lax.while_loop(
        cond, body, init
    )
    return dist[None], frontier[None], accum.pack_summary(
        rnd - round0, covered / n_live, (hi, lo)
    )


@functools.lru_cache(maxsize=64)
def _hopdist_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                    max_rounds: int, pieces=(), mxu_block: int = 128,
              comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_coverage_hopdist, axis_name, S, block,
                             pieces, mxu_block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda target, *args: body(target, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 14 + (P(),),
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def hopdist_until_coverage(sg: ShardedGraph, mesh: Mesh, protocol, *,
                           coverage_target: float = 0.99,
                           max_rounds: int = 1024,
                           axis_name: str = DEFAULT_AXIS, state0=None,
                           adaptive_k: int = 0, comm: str = DEFAULT_COMM):
    """BFS until the reached fraction of the LIVE population hits the
    target — engine.run_until_coverage's measurement for HopDistance,
    multi-chip — with an extra early exit the engine loop lacks: if the
    wave dies out first (unreachable remainder), the loop stops instead of
    spinning to ``max_rounds``. Returns ``((dist, frontier, round),
    dict(rounds, coverage, messages))``.

    ``adaptive_k > 0`` (requires ``shard_graph(source_csr=True)``) runs
    small-frontier rounds through the work-item sparse path — the same
    machinery, budget and bit-identity contract as
    ``flood_until_coverage(adaptive_k=...)``; BFS layers, rounds and
    message totals are unchanged."""
    S, block = sg.n_shards, sg.block
    if state0 is None:
        state0 = init_state(sg, protocol, None)
    dist0, frontier0, round0 = state0
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    mxu_src, mxu_dst, mxu_mask = _mxu_or_empty(sg)
    if adaptive_k > 0:
        if sg.csr_pos is None:
            raise ValueError(
                "adaptive_k requires a sender-CSR sharded graph — build "
                "with shard_graph(source_csr=True)"
            )
        fn = _hopdist_adaptive_cov_fn(
            mesh, axis_name, S, block, max_rounds, adaptive_k,
            max(sg.csr_span, 1), sg.diag_pieces, sg.mxu_block,
            _resolve_comm(comm),
        )
        dist, frontier, packed = fn(
            jnp.float32(coverage_target),
            sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
            mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
            sg.node_mask, sg.out_degree, sg.csr_pos, sg.csr_offsets,
            dist0, frontier0, round0,
        )
    else:
        fn = _hopdist_cov_fn(mesh, axis_name, S, block, max_rounds,
                             sg.diag_pieces, sg.mxu_block, _resolve_comm(comm))
        dist, frontier, packed = fn(
            jnp.float32(coverage_target),
            sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
            mxu_src, mxu_dst, mxu_mask, _diag_masks_or_empty(sg),
            sg.node_mask, sg.out_degree, dist0, frontier0, round0,
        )
    out = accum.unpack_summary(packed)
    rnd = round0 + out["rounds"]
    return (dist, frontier, rnd), out


def hopdist_until_done(sg: ShardedGraph, mesh: Mesh, protocol, *,
                       max_rounds: int = 1024,
                       axis_name: str = DEFAULT_AXIS, state0=None,
                       adaptive_k: int = 0, comm: str = DEFAULT_COMM):
    """BFS until the wave dies out (or ``max_rounds``): the complete
    single-source reachability / eccentricity measurement — the
    coverage loop with an unreachable target, so only frontier death
    stops it. ``rounds`` includes the final round that observes the
    emptied frontier (one past the last delivery); the max over ``dist``
    is the source's eccentricity. ``adaptive_k`` as in
    :func:`hopdist_until_coverage` — the sparse tail is where adaptive
    rounds pay off most (the wave's last layers are a trickle)."""
    return hopdist_until_coverage(
        sg, mesh, protocol, coverage_target=2.0, max_rounds=max_rounds,
        axis_name=axis_name, state0=state0, adaptive_k=adaptive_k,
        comm=comm,
    )


# ----------------------------------------- frontier-adaptive coverage loop


def _pack_global_frontier(axis_name, S, k, local_ids, local_count, pad_id):
    """Combine per-shard winner lists into one REPLICATED global frontier
    list: all-gather the (tiny) per-shard [k] lists + counts, then every
    shard deterministically packs them at running offsets — identical
    output everywhere, so the list can drive replicated control flow.
    Truncation past ``k`` is benign: the total then exceeds ``k`` and the
    next round runs dense, never reading the list."""
    lists = jax.lax.all_gather(local_ids, axis_name)  # [S, k]
    counts = jax.lax.all_gather(local_count, axis_name)  # [S]
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
    )
    out = jnp.full(k, pad_id, dtype=jnp.int32)
    idx = jnp.arange(k, dtype=jnp.int32)
    for s in range(S):
        tpos = offs[s] + idx
        valid = (idx < counts[s]) & (tpos < k)
        out = out.at[jnp.where(valid, tpos, k)].set(
            jnp.where(valid, lists[s], pad_id), mode="drop"
        )
    return out, jnp.sum(counts).astype(jnp.int32)


def _make_adaptive_wave(axis_name, S, block, pieces, mxu_block, comm, k, span,
                        bkt_src, bkt_dst, bkt_mask,
                        dyn_src, dyn_dst, dyn_mask,
                        mxu_src, mxu_dst, mxu_mask, diag_masks,
                        node_mask, out_degree, csr_pos, csr_offsets):
    """Build the adaptive wave-round closures shared by the run-to-coverage
    flood and the adaptive BFS loops: rounds with a small global frontier
    skip the ring entirely — the frontier rides as a replicated index
    list, and each shard gathers only ITS edges from those senders
    through the sender-CSR view, chunked into W-wide WORK ITEMS (O(k·W)
    work and one tiny all-gather, instead of O(E/S) bucket work and S
    ppermute hops). Budgeting is by out-edge mass: the sparse branch runs
    while the largest per-shard item count fits ``k``, so a hub whose row
    rivals the budget tips the round dense instead of widening every
    gather to its degree (the multi-chip mirror of
    models/adaptive_flood.py's hub tolerance); results stay bit-identical
    to the dense loop.

    Returns ``(sparse_round, dense_round, my_new_ids, item_budget,
    n_live)`` — both rounds map ``(seen, frontier, F, fncount, ficount)
    -> (seen, frontier, F, fncount, ficount, msgs)``."""
    pass_ = _make_or_pass(axis_name, S, block, pieces, mxu_block, comm,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks)
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    csr_pos_b, csr_offsets_b = csr_pos[0], csr_offsets[0]
    flat_mask = bkt_mask[0].reshape(-1)
    flat_dst = bkt_dst[0].reshape(-1)
    dyn_src_b, dyn_dst_b, dyn_mask_b = dyn_src[0], dyn_dst[0], dyn_mask[0]
    has_dyn = dyn_src_b.shape[-1] > 0
    n_g = S * block
    pad_id = n_g - 1
    w = max(1, min(span, 128))  # work-item slice width
    my = jax.lax.axis_index(axis_name)
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )
    idx_k = jnp.arange(k, dtype=jnp.int32)

    def my_new_ids(new_local_mask, local_count):
        """This shard's new nodes as global ids, [k]-padded."""
        lpos = jnp.nonzero(new_local_mask, size=k, fill_value=block - 1)[0]
        return jnp.where(idx_k < local_count,
                         my * block + lpos.astype(jnp.int32), pad_id)

    def item_budget(F, ncount):
        """Replicated sparse-mode budget for frontier list ``F``: the
        largest per-shard W-slice work-item count (pmax), saturated past
        ``k`` when the node list itself overflowed (truncated F is never
        read). Every shard computes the identical value, so it can drive
        the replicated sparse/dense branch."""
        fvalid = idx_k < ncount
        f = jnp.where(fvalid, F, pad_id)
        row_len = csr_offsets_b[f + 1] - csr_offsets_b[f]
        items = jnp.where(fvalid, (row_len + w - 1) // w, 0)
        icount = jax.lax.pmax(jnp.sum(items).astype(jnp.int32), axis_name)
        return jnp.where(ncount > k, jnp.int32(k + 1), icount)

    def sparse_round(seen, frontier, F, fncount, ficount):
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        # Expand the replicated node list into THIS shard's work items
        # (cumsum + searchsorted over k entries): item p covers slots
        # [base + slice*w, ...) of its owning node's local CSR row.
        fvalid = idx_k < fncount
        f = jnp.where(fvalid, F, pad_id)
        base_row = csr_offsets_b[f]
        row_end = csr_offsets_b[f + 1]
        if span <= w:
            # STATIC fast path (span and w are trace-time ints, the
            # engine's _one_item_per_node twin): no per-shard row chunks,
            # so item p IS node list entry p — in sparse mode the node
            # count is <= k by the budget's saturation, so the direct
            # mapping covers every entry and empty local rows simply
            # contribute no slots. Skips the cumsum + searchsorted.
            slot = base_row[:, None] + jnp.arange(w)[None, :]  # [k, w]
            svalid = (slot < row_end[:, None]) & fvalid[:, None]
        else:
            items_per = jnp.where(fvalid,
                                  (row_end - base_row + w - 1) // w, 0)
            offs = jnp.cumsum(items_per)
            starts = offs - items_per
            icount_local = offs[-1]
            j = jnp.clip(jnp.searchsorted(offs, idx_k, side="right"),
                         0, k - 1)
            ivalid = idx_k < icount_local
            base = base_row[j] + (idx_k - starts[j]) * w
            slot = base[:, None] + jnp.arange(w)[None, :]  # [k, w]
            svalid = (slot < row_end[j][:, None]) & ivalid[:, None]
        pos = csr_pos_b[jnp.where(svalid, slot, 0)]
        evalid = (svalid & flat_mask[pos]).reshape(-1)
        cand = jnp.where(evalid, flat_dst[pos].reshape(-1), block - 1)
        fresh = evalid & ~seen[cand] & node_mask_b[cand]
        if has_dyn:
            # Dynamic out-edges: reconstruct the global sender from the
            # ring step, membership-test against the frontier list via
            # binary search in the sorted list — O(E_dyn·log k), where the
            # naive broadcast compare is O(E_dyn·k) and can rival the
            # dense pass with a generous dynamic capacity (ADVICE r3).
            # The -1 sentinel (never a node id) keeps padded F entries
            # from matching a live spare node.
            t_i = jnp.arange(S, dtype=jnp.int32)[:, None]
            g_send = ((my - t_i) % S) * block + dyn_src_b
            probe = jnp.sort(jnp.where(fvalid, F, -1))
            j = jnp.clip(jnp.searchsorted(probe, g_send), 0, k - 1)
            member = (probe[j] == g_send) & dyn_mask_b
            dcand = jnp.where(member, dyn_dst_b, block - 1).reshape(-1)
            dfresh = (member.reshape(-1) & ~seen[dcand]
                      & node_mask_b[dcand])
            cand = jnp.concatenate([cand, dcand])
            fresh = jnp.concatenate([fresh, dfresh])
        # First-claim dedup onto this shard's node block (each shard owns
        # its receivers, so dedup is purely local).
        order = jnp.arange(cand.shape[0], dtype=jnp.int32)
        big = jnp.int32(2**31 - 1)
        claim = jnp.where(fresh, order, big)
        scratch = jnp.full(block, big, dtype=jnp.int32).at[cand].min(claim)
        winner = fresh & (scratch[cand] == order)
        local_count = jnp.sum(winner).astype(jnp.int32)
        seen = seen.at[jnp.where(fresh, cand, block)].set(True, mode="drop")
        frontier = (
            jnp.zeros(block, dtype=bool)
            .at[jnp.where(winner, cand, block)].set(True, mode="drop")
        )
        wpos = jnp.nonzero(winner, size=k, fill_value=cand.shape[0] - 1)[0]
        local_ids = jnp.where(idx_k < local_count,
                              my * block + cand[wpos], pad_id)
        F, ncount = _pack_global_frontier(axis_name, S, k, local_ids,
                                          local_count, pad_id)
        return seen, frontier, F, ncount, item_budget(F, ncount), msgs

    def dense_round(seen, frontier, F, fncount, ficount):
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        delivered = pass_(frontier)
        new = delivered & ~seen & node_mask_b
        seen = seen | new
        local_count = jnp.sum(new).astype(jnp.int32)
        ncount = jax.lax.psum(local_count, axis_name)

        def compact(_):
            return _pack_global_frontier(
                axis_name, S, k, my_new_ids(new, local_count), local_count,
                pad_id,
            )[0]

        F = jax.lax.cond(ncount <= k, compact, lambda _: F, None)
        # item_budget saturates to k+1 when ncount > k, so the stale F of
        # the non-compacted branch is never trusted.
        return seen, new, F, ncount, item_budget(F, ncount), msgs

    return sparse_round, dense_round, my_new_ids, item_budget, n_live


def _ring_adaptive_cov_or(axis_name, S, block, pieces, mxu_block, comm, k, span,
                          coverage_target, max_rounds,
                          bkt_src, bkt_dst, bkt_mask,
                          dyn_src, dyn_dst, dyn_mask,
                          mxu_src, mxu_dst, mxu_mask, diag_masks,
                          node_mask, out_degree, csr_pos, csr_offsets,
                          seen0, frontier0):
    """Per-shard body: run-to-coverage flood on the adaptive wave rounds
    (see :func:`_make_adaptive_wave` for the work-item machinery)."""
    sparse_round, dense_round, my_new_ids, item_budget, n_live = (
        _make_adaptive_wave(axis_name, S, block, pieces, mxu_block, comm, k, span,
                            bkt_src, bkt_dst, bkt_mask,
                            dyn_src, dyn_dst, dyn_mask,
                            mxu_src, mxu_dst, mxu_mask, diag_masks,
                            node_mask, out_degree, csr_pos, csr_offsets)
    )
    node_mask_b = node_mask[0]
    pad_id = S * block - 1

    def cond(carry):
        _, _, _, _, _, rounds, covered, _, _, _ = carry
        return (covered / n_live < coverage_target) & (rounds < max_rounds)

    def body(carry):
        (seen, frontier, F, fncount, ficount, rounds, prev_covered,
         hi, lo, occ) = carry
        seen, frontier, F, fncount, ficount, msgs = jax.lax.cond(
            ficount <= k, sparse_round, dense_round,
            seen, frontier, F, fncount, ficount,
        )
        hi, lo = accum.add((hi, lo), msgs)
        covered = jax.lax.psum(
            jnp.sum((seen & node_mask_b).astype(jnp.int32)), axis_name
        )
        # Same ints as the dense loop and the engine (ops/frontier.py
        # occupancy) — the adaptive and dense summaries must stay
        # bit-identical (tests pin `out_a == out_d`). The new frontier's
        # live count IS the coverage delta, so no extra psum per round.
        occ = occ + ((covered - prev_covered) / n_live).astype(jnp.float32)
        return (seen, frontier, F, fncount, ficount, rounds + 1, covered,
                hi, lo, occ)

    seen_b, frontier_b = seen0[0], frontier0[0]
    count0 = jnp.sum(frontier_b).astype(jnp.int32)
    F0, ncount0 = _pack_global_frontier(
        axis_name, S, k, my_new_ids(frontier_b, count0), count0, pad_id
    )
    covered0 = jax.lax.psum(
        jnp.sum((seen_b & node_mask_b).astype(jnp.int32)), axis_name
    )
    init = (seen_b, frontier_b, F0, ncount0, item_budget(F0, ncount0),
            jnp.int32(0), covered0, *accum.zero(), jnp.float32(0.0))
    seen, frontier, _, _, _, rounds, covered, hi, lo, occ = jax.lax.while_loop(
        cond, body, init
    )
    return seen[None], frontier[None], accum.pack_summary(
        rounds, covered / n_live, (hi, lo),
        extra=occ / jnp.maximum(rounds, 1)
    )


@functools.lru_cache(maxsize=64)
def _flood_adaptive_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                           max_rounds: int, k: int, span: int, pieces=(),
                           mxu_block: int = 128,
                           comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_adaptive_cov_or, axis_name, S, block,
                             pieces, mxu_block, comm, k, span)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda target, *args: body(target, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 16,
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def _ring_adaptive_cov_hopdist(axis_name, S, block, pieces, mxu_block, comm, k,
                               span, coverage_target, max_rounds,
                               bkt_src, bkt_dst, bkt_mask,
                               dyn_src, dyn_dst, dyn_mask,
                               mxu_src, mxu_dst, mxu_mask, diag_masks,
                               node_mask, out_degree, csr_pos, csr_offsets,
                               dist0, frontier0, round0):
    """Per-shard body: BFS on the adaptive wave rounds — loop semantics of
    :func:`_ring_coverage_hopdist` (stop on coverage, wave death, or
    max_rounds), wave mechanics of :func:`_make_adaptive_wave`. ``seen``
    is carried explicitly alongside ``dist`` so the round closures stay
    shared with the flood loop; the two are linked by ``seen == (dist >=
    0)`` at every step."""
    sparse_round, dense_round, my_new_ids, item_budget, n_live = (
        _make_adaptive_wave(axis_name, S, block, pieces, mxu_block, comm, k, span,
                            bkt_src, bkt_dst, bkt_mask,
                            dyn_src, dyn_dst, dyn_mask,
                            mxu_src, mxu_dst, mxu_mask, diag_masks,
                            node_mask, out_degree, csr_pos, csr_offsets)
    )
    node_mask_b = node_mask[0]
    pad_id = S * block - 1

    def cond(carry):
        _, _, _, _, fncount, _, rnd, covered, _, _ = carry
        return ((fncount > 0) & (rnd - round0 < max_rounds)
                & (covered / n_live < coverage_target))

    def body(carry):
        seen, dist, frontier, F, fncount, ficount, rnd, _, hi, lo = carry
        seen, frontier, F, fncount, ficount, msgs = jax.lax.cond(
            ficount <= k, sparse_round, dense_round,
            seen, frontier, F, fncount, ficount,
        )
        rnd = rnd + 1
        dist = jnp.where(frontier, rnd, dist)
        hi, lo = accum.add((hi, lo), msgs)
        covered = jax.lax.psum(
            jnp.sum((seen & node_mask_b).astype(jnp.int32)), axis_name
        )
        return seen, dist, frontier, F, fncount, ficount, rnd, covered, hi, lo

    dist_b, frontier_b = dist0[0], frontier0[0]
    seen_b = (dist_b >= 0) & node_mask_b
    count0 = jnp.sum(frontier_b).astype(jnp.int32)
    F0, ncount0 = _pack_global_frontier(
        axis_name, S, k, my_new_ids(frontier_b, count0), count0, pad_id
    )
    covered0 = jax.lax.psum(
        jnp.sum(seen_b.astype(jnp.int32)), axis_name
    )
    init = (seen_b, dist_b, frontier_b, F0, ncount0,
            item_budget(F0, ncount0), round0, covered0, *accum.zero())
    _, dist, frontier, _, _, _, rnd, covered, hi, lo = jax.lax.while_loop(
        cond, body, init
    )
    return dist[None], frontier[None], accum.pack_summary(
        rnd - round0, covered / n_live, (hi, lo)
    )


@functools.lru_cache(maxsize=64)
def _hopdist_adaptive_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                             max_rounds: int, k: int, span: int, pieces=(),
                             mxu_block: int = 128,
                             comm: str = DEFAULT_COMM):
    body = functools.partial(_ring_adaptive_cov_hopdist, axis_name, S,
                             block, pieces, mxu_block, comm, k, span)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        lambda target, *args: body(target, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 16 + (P(),),
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


# ------------------------------------------------------------ random walks


def _make_walk_round(axis_name, S, block, W, span, restart_p,
                     bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                     node_mask, csr_pos, csr_offsets):
    """Per-shard walker-cohort round (models/walk.py, multi-chip).

    The cohort's positions ride REPLICATED [W]; each shard owns the
    edges INTO its node block, so it scores exactly the candidates the
    engine would gather for those receivers — through the per-shard
    sender-CSR over the bucket arrays (liveness re-masks and disconnects
    apply with no rebuild). Because every candidate's uniform is keyed
    by the edge IDENTITY (utils/edgehash.py), not its slot, the global
    argmax = pmax of per-shard maxima reproduces the engine's choice
    bit-for-bit: equal-u ties break on the higher receiver id, composed
    here as a second pmax over the per-shard best receivers among
    global-max holders.

    Returns ``one_round(pos, start, alive_start, visited_b, key) ->
    (pos, visited_b, moved, can_move, covered)``.
    """
    from p2pnetwork_tpu.utils.edgehash import edge_uniform

    node_mask_b = node_mask[0]
    csr_pos_b, csr_offsets_b = csr_pos[0], csr_offsets[0]
    flat_mask = bkt_mask[0].reshape(-1)
    flat_dst = bkt_dst[0].reshape(-1)
    dyn_src_b, dyn_dst_b, dyn_mask_b = dyn_src[0], dyn_dst[0], dyn_mask[0]
    has_dyn = dyn_src_b.shape[-1] > 0
    my = jax.lax.axis_index(axis_name)
    w = max(span, 1)
    walkers = jnp.arange(W, dtype=jnp.int32)

    def one_round(pos, start, alive_start, visited, key):
        # Same split as RandomWalks.step — the engine and every shard
        # derive identical sub-keys from the identical round key.
        k_edge, k_restart = jax.random.split(key)

        base = csr_offsets_b[pos]
        end = csr_offsets_b[pos + 1]
        slot = base[:, None] + jnp.arange(w)[None, :]
        svalid = slot < end[:, None]  # out-of-row slots masked (csr_pos
        # padding stays in bounds but can alias live slots — same
        # contract as the adaptive wave)
        p = csr_pos_b[jnp.where(svalid, slot, 0)]
        dst_local = flat_dst[p]
        rcv = my * block + dst_local
        live = svalid & flat_mask[p] & node_mask_b[dst_local]
        u = jnp.where(live,
                      edge_uniform(k_edge, walkers[:, None], pos[:, None],
                                   rcv),
                      -1.0)
        m_loc = jnp.max(u, axis=1)
        r_loc = jnp.max(jnp.where(live & (u == m_loc[:, None]), rcv, -1),
                        axis=1)
        if has_dyn:
            # Dynamic out-edges: reconstruct global senders from the ring
            # step, membership-test against the cohort ([W, S, K]).
            t_i = jnp.arange(S, dtype=jnp.int32)[:, None]
            g_send = ((my - t_i) % S) * block + dyn_src_b  # [S, K]
            member = ((g_send[None] == pos[:, None, None])
                      & dyn_mask_b[None]
                      & node_mask_b[dyn_dst_b][None])  # [W, S, K]
            drcv = jnp.broadcast_to((my * block + dyn_dst_b)[None],
                                    member.shape)
            du = jnp.where(member,
                           edge_uniform(k_edge, walkers[:, None, None],
                                        pos[:, None, None], drcv),
                           -1.0).reshape(W, -1)
            dm = jnp.max(du, axis=1)
            dr = jnp.max(jnp.where(
                member.reshape(W, -1) & (du == dm[:, None]),
                drcv.reshape(W, -1), -1), axis=1)
            r_loc = jnp.where(dm > m_loc, dr,
                              jnp.where(dm == m_loc, jnp.maximum(r_loc, dr),
                                        r_loc))
            m_loc = jnp.maximum(m_loc, dm)

        m = jax.lax.pmax(m_loc, axis_name)  # [W], replicated
        r = jax.lax.pmax(
            jnp.where((m_loc == m) & (m >= 0), r_loc, -1), axis_name
        )
        can_move = m >= 0.0
        dest = jnp.where(can_move, r, pos)

        if restart_p > 0.0:
            restart = (
                (jax.random.uniform(k_restart, (W,)) < restart_p)
                & alive_start
            )
            dest = jnp.where(restart, start, dest)
            moved = (restart | can_move) & (dest != pos)
        else:
            moved = can_move & (dest != pos)

        owned = (dest // block) == my
        visited = (
            visited.at[jnp.where(owned, dest % block, block)]
            .set(True, mode="drop")
            & node_mask_b
        )
        covered = jax.lax.psum(
            jnp.sum((visited & node_mask_b).astype(jnp.int32)), axis_name
        )
        return dest, visited, moved, can_move, covered

    return one_round


def _ring_rounds_walk(axis_name, S, block, W, span, restart_p,
                      bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                      node_mask, csr_pos, csr_offsets,
                      pos0, start0, alive_start, visited0, round_keys):
    one_round = _make_walk_round(axis_name, S, block, W, span, restart_p,
                                 bkt_dst, bkt_mask, dyn_src, dyn_dst,
                                 dyn_mask, node_mask, csr_pos, csr_offsets)
    node_mask_b = node_mask[0]
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )

    def body(carry, rkey):
        pos, visited = carry
        pos, visited, moved, can_move, covered = one_round(
            pos, start0, alive_start, visited,
            jax.random.wrap_key_data(rkey),
        )
        stats = {
            "messages": jnp.sum(moved),
            "coverage": covered / n_live,
            "stuck": jnp.sum(~can_move),
        }
        return (pos, visited), stats

    (pos, visited), stats = jax.lax.scan(body, (pos0, visited0[0]),
                                         round_keys)
    return pos, visited[None], stats


@functools.lru_cache(maxsize=64)
def _walk_fn(mesh: Mesh, axis_name: str, S: int, block: int,
             W: int, span: int, restart_p: float):
    """The scan length rides on round_keys' shape, so the round count is
    deliberately NOT part of this cache key (jit retraces on shape)."""
    body = functools.partial(_ring_rounds_walk, axis_name, S, block, W,
                             span, restart_p)
    spec = P(axis_name)
    fn = shard_map(
        body, mesh=mesh, check_vma=False,
        in_specs=(spec,) * 8 + (P(), P(), P(), spec, P()),
        out_specs=(P(), spec, P()),
    )
    return jax.jit(fn)


def _ring_cov_walk(axis_name, S, block, W, span, restart_p, steps_per_round,
                   coverage_target, max_rounds,
                   bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                   node_mask, csr_pos, csr_offsets,
                   pos0, start0, alive_start, visited0, key_data):
    one_round = _make_walk_round(axis_name, S, block, W, span, restart_p,
                                 bkt_dst, bkt_mask, dyn_src, dyn_dst,
                                 dyn_mask, node_mask, csr_pos, csr_offsets)
    node_mask_b = node_mask[0]
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(node_mask_b.astype(jnp.int32)), axis_name), 1
    )

    def one_step(state):
        pos, visited, kd = state
        # Chained split, mirroring engine._stat_while round for round.
        k, sub = jax.random.split(jax.random.wrap_key_data(kd))
        pos, visited, moved, _, covered = one_round(
            pos, start0, alive_start, visited, sub
        )
        return (pos, visited, jax.random.key_data(k)), covered, \
            jnp.sum(moved)

    covered0 = jax.lax.psum(
        jnp.sum((visited0[0] & node_mask_b).astype(jnp.int32)), axis_name
    )
    (pos, visited, _), rounds, covered, (hi, lo) = _freeze_while(
        (pos0, visited0[0], key_data), covered0, one_step,
        lambda cov, r: (cov / n_live < coverage_target) & (r < max_rounds),
        steps_per_round)
    return pos, visited[None], accum.pack_summary(
        rounds, covered / n_live, (hi, lo)
    )


@functools.lru_cache(maxsize=64)
def _walk_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                 max_rounds: int, W: int, span: int, restart_p: float,
                 steps_per_round: int = 1):
    body = functools.partial(_ring_cov_walk, axis_name, S, block, W, span,
                             restart_p, steps_per_round)
    spec = P(axis_name)
    fn = shard_map(
        lambda target, *args: body(target, max_rounds, *args),
        mesh=mesh, check_vma=False,
        in_specs=(P(),) + (spec,) * 8 + (P(), P(), P(), spec, P()),
        out_specs=(P(), spec, P()),
    )
    return jax.jit(fn)


def _walk_require_csr(sg: ShardedGraph):
    if sg.csr_pos is None:
        raise ValueError(
            "the sharded walk requires a sender-CSR sharded graph — build "
            "with shard_graph(source_csr=True)"
        )


def _walk_state0(sg: ShardedGraph, protocol):
    """RandomWalks.init parity on the sharded representation — a one-off
    host-side O(N) setup (eager jnp on mesh-sharded operands would trip
    sharding propagation outside a mesh context)."""
    mask = np.asarray(sg.node_mask).reshape(-1)
    n_pad = sg.n_shards * sg.block
    live_ids = np.flatnonzero(mask)
    if live_ids.size:
        n_live = live_ids.size
        stride = max(n_live // protocol.n_walkers, 1)
        pos = live_ids[
            (np.arange(protocol.n_walkers) * stride) % n_live
        ].astype(np.int32)
    else:
        pos = np.zeros(protocol.n_walkers, np.int32)
    visited = np.zeros(n_pad, dtype=bool)
    visited[pos] = True
    visited &= mask
    return (jnp.asarray(pos), jnp.asarray(pos),
            jnp.asarray(visited.reshape(sg.n_shards, sg.block)))


def _walk_call(sg: ShardedGraph, protocol, state0):
    """Shared argument marshalling for walk()/walk_until_coverage()."""
    if state0 is None:
        pos0, start0, visited0 = _walk_state0(sg, protocol)
    else:
        pos0, start0, visited0 = state0
    # Host-side gather for the same reason as _walk_state0.
    alive_start = jnp.asarray(
        np.asarray(sg.node_mask).reshape(-1)[np.asarray(start0)]
    )
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    common = (sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst, dyn_mask,
              sg.node_mask, sg.csr_pos, sg.csr_offsets)
    return common, pos0, start0, alive_start, visited0


def walk(sg: ShardedGraph, mesh: Mesh, protocol, key: jax.Array,
         rounds: int, axis_name: str = DEFAULT_AXIS, state0=None,
         return_state: bool = False):
    """Run ``rounds`` of the walker cohort (models/walk.py RandomWalks) on
    the sharded graph — bit-identical to ``engine.run(graph, protocol,
    key, rounds)`` for any shard count, because candidate draws are keyed
    by edge identity (utils/edgehash.py), not layout.

    Returns ``(visited [S, block] bool, stats dict of [rounds] arrays)``;
    with ``return_state=True``, ``((pos, start, visited), stats)`` — the
    resume triple ``walk_until_coverage`` also accepts.
    """
    _walk_require_csr(sg)
    S, block = sg.n_shards, sg.block
    common, pos0, start0, alive_start, visited0 = _walk_call(
        sg, protocol, state0)
    keys = jax.random.split(jax.random.fold_in(key, 1), rounds)
    fn = _walk_fn(mesh, axis_name, S, block, protocol.n_walkers,
                  max(sg.csr_span, 1), float(protocol.restart_p))
    pos, visited, stats = fn(*common, pos0, start0, alive_start, visited0,
                             jax.random.key_data(keys))
    if return_state:
        return (pos, start0, visited), stats
    return visited, stats


def walk_until_coverage(sg: ShardedGraph, mesh: Mesh, protocol,
                        key: jax.Array, *,
                        coverage_target: float = 0.99,
                        max_rounds: int = 1024,
                        steps_per_round: int = 1,
                        axis_name: str = DEFAULT_AXIS, state0=None,
                        return_state: bool = False):
    """Walk until the cohort has visited ``coverage_target`` of the live
    population — ``engine.run_until_coverage`` with RandomWalks,
    multi-chip, one XLA program (the discovery question: rounds to map
    the overlay). Same identity-keyed draws as :func:`walk`, so the
    trajectory is bit-identical to the engine loop's for any shard count.

    ``steps_per_round=T`` batches T walk rounds per while-loop iteration
    (bit-exact vs T=1, same contract as ``engine.run_until_coverage``) —
    the crawl is rounds-bound at a per-iteration floor set by dispatch
    and the ring's collectives, which T amortizes.

    Returns ``(visited, dict(rounds, coverage, messages))``; with
    ``return_state=True``, ``((pos, start, visited), dict)``.
    """
    _walk_require_csr(sg)
    if steps_per_round < 1:
        raise ValueError(
            f"steps_per_round must be >= 1, got {steps_per_round}")
    S, block = sg.n_shards, sg.block
    common, pos0, start0, alive_start, visited0 = _walk_call(
        sg, protocol, state0)
    fn = _walk_cov_fn(mesh, axis_name, S, block, max_rounds,
                      protocol.n_walkers, max(sg.csr_span, 1),
                      float(protocol.restart_p), int(steps_per_round))
    pos, visited, packed = fn(
        jnp.float32(coverage_target), *common, pos0, start0, alive_start,
        visited0, jax.random.key_data(key),
    )
    out = accum.unpack_summary(packed)
    if return_state:
        return (pos, start0, visited), out
    return visited, out


# --------------------------------------------- lane-word batched plane
#
# The PR-10 batched message plane packs 32 concurrent broadcast states per
# uint32 word (ops/bitset.py lane algebra; models/messagebatch.py). Here
# those lane words are the HALO PAYLOAD: the ring's resident block becomes
# ``u32[W, block]``, so ONE halo hop per ring step moves the boundary
# state of every in-flight message at once — 32·W messages per DMA — and
# the batched plane goes multi-chip without any new per-message traffic.


def _bucket_or_lanes(block, sorted_dst=True):
    """Word-level OR bucket for lane-packed payloads: the resident block
    is ``u32[W, block]``; one gather per word serves its 32 message
    lanes, and the per-edge OR is the bit-plane uint8 segment-max of
    ``ops/segment.propagate_or_lanes``'s segment method (word-level
    ``.at[].max`` cannot OR two different patterns landing on one
    receiver)."""
    from p2pnetwork_tpu.ops import bitset

    def apply(rot, src, dst, m):
        def word(wl):
            contrib = jnp.where(m, wl[src], jnp.uint32(0))
            planes = jax.ops.segment_max(
                bitset.expand_lanes(contrib).astype(jnp.uint8), dst,
                num_segments=block, indices_are_sorted=sorted_dst,
            )
            return bitset.collapse_lanes(planes > 0)

        return jax.vmap(word)(rot)

    return apply


def _make_or_lanes_pass(axis_name, S, block, comm,
                        bkt_src, bkt_dst, bkt_mask,
                        dyn_src, dyn_dst, dyn_mask):
    """Build ``pass_(lanes u32[W, block]) -> u32[W, block]``: one full
    ring rotation OR-ing every lane of every word over every incoming
    edge — :func:`_make_or_pass` lifted to the lane-packed carrier. The
    halo payload is the whole ``[W, block]`` word stack, so each ring
    step's single hop carries 32·W messages' boundary state. Segment
    buckets only (the MXU one-hot and diagonal layouts have no
    word-level form — callers gate)."""
    groups = [
        (_bucket_or_lanes(block, sorted_dst=True),
         bkt_src[0], bkt_dst[0], bkt_mask[0]),
        (_bucket_or_lanes(block, sorted_dst=False),
         dyn_src[0], dyn_dst[0], dyn_mask[0]),
    ]
    comm_obj = _make_ring_comm(comm, axis_name, S)

    def pass_(lanes):
        return _ring_pass(axis_name, S, lanes, groups,
                          jnp.zeros_like(lanes), jnp.bitwise_or,
                          comm=comm_obj)

    pass_.comm = comm_obj  # round-context handle for fault-wired loops
    return pass_


def _require_lanes_layout(sg: ShardedGraph, what: str) -> None:
    if sg.mxu_src is not None:
        raise ValueError(
            f"{what} cannot ride the MXU one-hot layout — shard_graph "
            "without hybrid/min_count for the lane-packed batched path "
            "(word-level OR has no one-hot-matmul form)"
        )


def _or_lanes_body(axis_name, S, block, comm,
                   bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                   node_mask, lanes):
    pass_ = _make_or_lanes_pass(axis_name, S, block, comm,
                                bkt_src, bkt_dst, bkt_mask,
                                dyn_src, dyn_dst, dyn_mask)
    nm = node_mask[0]
    node_lanes = jnp.where(nm, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    return (pass_(lanes[0]) & node_lanes[None, :])[None]


@functools.lru_cache(maxsize=64)
def _or_lanes_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                 comm: str = DEFAULT_COMM):
    body = functools.partial(_or_lanes_body, axis_name, S, block, comm)
    spec = P(axis_name)
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(body, mesh=mesh, check_vma=False,
                   in_specs=(spec,) * 8, out_specs=spec)
    return jax.jit(fn)


def shard_lanes(sg: ShardedGraph, lanes) -> jax.Array:
    """Place a lane-word stack ``u32[W, N_pad]`` (the single-device
    layout of ops/segment.propagate_or_lanes / MessageBatch predicates)
    on the mesh as ``[S, W, block]`` — node-blocked like every other
    sharded per-node array, zero-padding the node axis when the shard
    grid rounds it up."""
    lanes = jnp.asarray(lanes)
    w = lanes.shape[0]
    pad = sg.n_nodes_padded - lanes.shape[1]
    if pad:
        lanes = jnp.pad(lanes, ((0, 0), (0, pad)))
    blocked = lanes.reshape(w, sg.n_shards, sg.block).transpose(1, 0, 2)
    shard = NamedSharding(_mesh_of(sg), P(_mesh_of(sg).axis_names[0]))
    return jax.device_put(blocked, shard)


def unshard_lanes(sg: ShardedGraph, lanes: jax.Array,
                  n_pad: Optional[int] = None) -> jax.Array:
    """Inverse of :func:`shard_lanes`: ``[S, W, block] -> u32[W, n_pad]``
    (``n_pad`` defaults to the full shard grid ``S·block``)."""
    w = lanes.shape[1]
    flat = lanes.transpose(1, 0, 2).reshape(w, -1)
    return flat if n_pad is None else flat[:, :n_pad]


def propagate_or_lanes(sg: ShardedGraph, mesh: Mesh, lanes: jax.Array,
                       axis_name: str = DEFAULT_AXIS,
                       comm: str = DEFAULT_COMM) -> jax.Array:
    """Lane-packed neighbor-OR over the sharded graph: the multi-chip
    mirror of ``ops.segment.propagate_or_lanes`` — 32·W concurrent
    boolean signals advanced by one ring pass, the lane words as the
    halo payload. ``lanes`` is ``[S, W, block]`` (see
    :func:`shard_lanes`); returns the same layout, masked to live
    nodes. Dynamic (runtime-connected) edges fold in; requires the
    segment layout (no ``hybrid``/``min_count``)."""
    _require_lanes_layout(sg, "propagate_or_lanes")
    fn = _or_lanes_fn(mesh, axis_name, sg.n_shards, sg.block,
                      _resolve_comm(comm))
    dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
    return fn(sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
              dyn_src, dyn_dst, dyn_mask, sg.node_mask, lanes)


def _ring_batch_cov(axis_name, S, block, comm, max_rounds,
                    bkt_src, bkt_dst, bkt_mask, dyn_src, dyn_dst, dyn_mask,
                    node_mask, out_degree,
                    seen0, frontier0, sent0, source, admitted, done0,
                    rounds0, seen_count0, target,
                    ring0=None, ici_round=None, fault_round0=None):
    """Per-shard body: advance EVERY running lane of a lane-packed batch
    until all admitted lanes complete (or ``max_rounds``) — the
    multi-chip mirror of ``engine._batch_loop`` + ``BatchFlood.step``,
    arithmetic-identical per lane: same ``new = delivered & ~seen &
    live`` dedup against node-masked kernels, same incremental
    transpose-popcount coverage numerator (psum'd across shards), same
    freeze/latch semantics, same per-word u32 send subtotals folded into
    the two-limb counter, same union-frontier occupancy ints. The ring's
    halo payload is the whole ``[W, block]`` word stack — one hop per
    ring step moves every in-flight message's boundary state."""
    from p2pnetwork_tpu.ops import bitset

    pass_ = _make_or_lanes_pass(axis_name, S, block, comm,
                                bkt_src, bkt_dst, bkt_mask,
                                dyn_src, dyn_dst, dyn_mask)
    # graftquake round context: a fault-spec comm keys its sites on the
    # GLOBAL round (fault_round0 + r), so chunked serving drivers hit
    # the same sites an unchunked run would.
    wire_faults = (fault_round0 is not None
                   and getattr(pass_.comm, "wants_step", False))
    nm = node_mask[0]
    node_lanes = jnp.where(nm, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    deg_u = out_degree[0].astype(jnp.uint32)
    n_live = jnp.maximum(
        jax.lax.psum(jnp.sum(nm.astype(jnp.int32)), axis_name), 1
    )

    def lane_counts_psum(words):  # u32[W, block] -> global i32[capacity]
        per = jax.vmap(bitset.lane_counts)(words).reshape(-1)
        return jax.lax.psum(per, axis_name)

    rec = ring0 is not None

    def cond(carry):
        done, r = carry[3], carry[6]
        return jnp.any(admitted & ~done) & (r < max_rounds)

    def body(carry):
        seen, frontier, sent, done, rounds_l, seen_count, r, hi, lo, occ = \
            carry[:10]
        if wire_faults:
            pass_.comm.set_context(round=fault_round0 + r)
        live = admitted & ~done
        live_mask = bitset.pack_bits(live)  # u32[W] replicated
        front = frontier & live_mask[:, None]
        delivered = pass_(front) & node_lanes[None, :]
        new = delivered & ~seen & live_mask[:, None]
        seen = seen | new
        sent = sent | front  # every frontier node broadcasts once
        # Per-word aggregate sends (u32-safe to E <= 2^27 globally, the
        # messagebatch contract) — psum'd per word, folded per word into
        # the exact two-limb total like engine._add_words.
        msgs_words = jax.lax.psum(
            jax.vmap(lambda f: jnp.sum(deg_u * jax.lax.population_count(f))
                     )(front),
            axis_name,
        )

        def fold(i, a):
            return accum.add(a, msgs_words[i])

        hi2, lo2 = jax.lax.fori_loop(0, msgs_words.shape[0], fold, (hi, lo))
        new_counts = lane_counts_psum(new)
        seen_count = seen_count + new_counts
        coverage = seen_count / n_live
        done = done | (admitted & (coverage >= target))
        rounds_l = rounds_l + live.astype(jnp.int32)
        next_mask = bitset.pack_bits(admitted & ~done)
        frontier = new & next_mask[:, None]
        # Union-frontier occupancy: the engine's exact ints
        # (ops/frontier.occupancy of the across-words OR), psum'd.
        union = jnp.any(frontier != 0, axis=0)
        occ_cnt = jax.lax.psum(
            jnp.sum((union & nm).astype(jnp.int32)), axis_name
        )
        occ = occ + (occ_cnt / n_live).astype(jnp.float32)
        out = (seen, frontier, sent, done, rounds_l, seen_count, r + 1,
               hi2, lo2, occ)
        if not rec:
            return out
        # Flight-recorder row: every value psum'd/replicated, so the
        # ring stays replicated (engine._batch_loop_rec's columns).
        return out + (flightrec.write_row(
            carry[10], r,
            occupancy=(occ_cnt / n_live).astype(jnp.float32),
            new=jnp.sum(msgs_words.astype(jnp.float32)),
            total=flightrec.total_f32(hi2, lo2),
            coverage=jnp.sum(seen_count.astype(jnp.float32)),
            active_lanes=jnp.sum((admitted & ~done).astype(jnp.int32)),
            ici_bytes=ici_round),)

    init = (seen0[0], frontier0[0], sent0[0], done0, rounds0, seen_count0,
            jnp.int32(0), *accum.zero(), jnp.float32(0.0))
    if rec:
        init = init + (ring0,)
    final = jax.lax.while_loop(cond, body, init)
    (seen, frontier, sent, done, rounds_l, seen_count, r, hi, lo, occ) = \
        final[:10]
    packed = accum.pack_batch_summary(
        r,
        jnp.sum((admitted & ~done).astype(jnp.int32)),
        jnp.sum(done.astype(jnp.int32)),
        (hi, lo),
        occ / jnp.maximum(r, 1),
        bitset.pack_bits(done),
        rounds_l,
    )
    out = (seen[None], frontier[None], sent[None], source, admitted, done,
           rounds_l, seen_count, target, packed)
    if rec:
        return out + (final[10],)
    return out


@functools.lru_cache(maxsize=64)
def _batch_cov_fn(mesh: Mesh, axis_name: str, S: int, block: int,
                  max_rounds: int, comm: str = DEFAULT_COMM,
                  donate: bool = False, rec: bool = False):
    """The compiled sharded batched-flood loop. ``donate=True`` builds
    the carry-donating variant (the 9 MessageBatch leaves alias the
    loop's buffers — the same contract engine's ``batch_from`` audits;
    graftaudit's donation audit covers this seam too). ``rec=True``
    appends the replicated flight ring + static per-round ICI estimate
    to the arguments and the ring to the outputs; the ring joins the
    donated carry."""
    body = functools.partial(_ring_batch_cov, axis_name, S, block, comm,
                             max_rounds)
    spec = P(axis_name)
    # A fault-spec comm (graftquake) appends the global first-round
    # scalar LAST — after the recorder pair when present — so the
    # donated carry indices below never move and string-comm programs
    # keep their exact pre-fault signature.
    faulty = not isinstance(comm, str)
    wrapped = body if not faulty else (
        lambda *a: body(*a[:-1], fault_round0=a[-1]))
    # check_vma=False: see the note on the sibling ring-body factories.
    fn = shard_map(
        wrapped, mesh=mesh, check_vma=False,
        in_specs=(spec,) * 11 + (P(),) * 6 + ((P(), P()) if rec else ())
        + ((P(),) if faulty else ()),
        out_specs=(spec,) * 3 + (P(),) * 6 + (P(),)
        + ((P(),) if rec else ()),
    )
    donate_argnums = ()
    if donate:
        # The 9 MessageBatch carry leaves — plus the flight ring when
        # recording (arg 17; the trailing ICI scalar is not a carry).
        donate_argnums = tuple(range(8, 17)) + ((17,) if rec else ())
    return jax.jit(fn, donate_argnums=donate_argnums)


def _shard_batch_args(sg: ShardedGraph, batch):
    """Marshal a MessageBatch onto the mesh: packed predicates blocked
    ``[S, W, block]`` (node axis zero-padded to the shard grid), per-lane
    metadata replicated."""
    mesh = _mesh_of(sg)
    rep = NamedSharding(mesh, P())
    put = lambda x: jax.device_put(jnp.asarray(x), rep)  # noqa: E731
    return (
        shard_lanes(sg, batch.seen), shard_lanes(sg, batch.frontier),
        shard_lanes(sg, batch.sent),
        put(batch.source), put(batch.admitted), put(batch.done),
        put(batch.rounds), put(batch.seen_count), put(batch.target),
    )


def run_batch_until_coverage(sg: ShardedGraph, mesh: Mesh, protocol,
                             batch, key=None, *,
                             max_rounds: int = 1024,
                             axis_name: str = DEFAULT_AXIS,
                             comm: str = DEFAULT_COMM,
                             donate: bool = True, recorder=None,
                             fault_round0: int = 0):
    """Advance ALL in-flight messages of a lane-packed batch on the
    SHARDED graph until every admitted lane reaches its coverage target —
    ``engine.run_batch_until_coverage`` on the multi-chip ring, one XLA
    program, the lane words as the halo payload (one hop per ring step
    moves 32·W messages' boundary state; ``comm`` picks ppermute or the
    Pallas ring-DMA kernels).

    ``batch`` is a plain single-device
    :class:`~p2pnetwork_tpu.models.messagebatch.MessageBatch` (built by
    ``protocol.init`` / ``admit`` against the UNSHARDED graph — the
    admission control plane stays host-side); it is marshalled onto the
    mesh per call and the returned batch is back in the single-device
    layout, so ``admit``/``retire``/``lane_seen`` and the engine loop
    interoperate freely. Per-lane results, round counts and the summary
    dict are BIT-IDENTICAL to the engine loop on the same batch
    (tests/test_ring.py pins the sweep). ``protocol`` supplies the
    entry-refresh semantics; its ``method`` is not consulted — the
    sharded path has exactly one lane lowering (segment buckets over the
    ring), like :func:`flood` vs ``Flood.method``. ``key`` is accepted
    for engine-signature symmetry and unused (the batched flood is
    deterministic). Requires the segment layout (no
    ``hybrid``/``min_count``).

    ``donate=True`` donates the loop's mesh-resident carry buffers —
    and, exactly like the engine loop's contract, treats the passed-in
    ``batch`` as CONSUMED (marshalling may alias rather than copy a
    leaf, e.g. replicated metadata on a host-backed mesh, so a donated
    run can invalidate it; resuming it raises the engine's friendly
    deleted-buffer error). Pass ``donate=False`` to keep reading the
    pre-run batch or to run the same batch through several loops — the
    parity tests do.

    ``recorder`` rides the per-round flight ring in the donated
    replicated carry (``ici_bytes`` column = this config's static
    per-round comm-census estimate) and attaches
    ``out["flight_record"]``; results stay bit-identical on both comm
    backends. The trace plane's ``batch_run`` span and per-lane
    lifecycle events mirror the engine loop's (``loop="sharded"``).

    ``comm`` also accepts a graftquake
    :class:`~p2pnetwork_tpu.chaos.device.FaultSpec` — seeded halo-hop
    faults keyed on the global round ``fault_round0 + r`` (chunked
    drivers pass ``fault_round0`` = the batch's cumulative round so
    chunk boundaries never move a fault site), counted into
    ``chaos_device_faults_total{kind}`` after the run.
    """
    from p2pnetwork_tpu.chaos import device as chaos_device
    from p2pnetwork_tpu.sim import engine as _engine

    chaos_device.dispatch_gate("sharded-batch")
    _require_lanes_layout(sg, "sharded run_batch_until_coverage")
    del key  # engine-signature symmetry; the batched flood draws nothing
    t0 = time.perf_counter()
    _engine._check_not_donated(batch)
    done0 = np.asarray(batch.done)
    tracer = spans.current_tracer()
    admitted0 = np.asarray(batch.admitted) if tracer is not None else None
    rounds0 = np.asarray(batch.rounds) if tracer is not None else None
    with spans.span("batch_run", loop="sharded", max_rounds=max_rounds):
        if tracer is not None:
            _engine._emit_batch_entry_events(admitted0, done0, rounds0)
        # Entry-time refresh — the batched cov0 seeding
        # (BatchFlood.refresh), against the sharded graph's CURRENT node
        # mask, host-fetched once: eager jnp on mesh-sharded operands
        # outside a mesh context trips sharding propagation (the
        # _walk_state0 rule), and refresh replaces only the two small
        # metadata leaves.
        from p2pnetwork_tpu.ops import bitset

        nm_host = _host_fetch(sg.node_mask).reshape(-1)[: batch.seen.shape[1]]
        node_lanes = jnp.where(jnp.asarray(nm_host), jnp.uint32(0xFFFFFFFF),
                               jnp.uint32(0))
        seen_count = jax.vmap(bitset.lane_counts)(
            batch.seen & node_lanes[None, :]).reshape(-1)
        n_live = jnp.maximum(jnp.int32(int(nm_host.sum())), 1)
        done = batch.done | (batch.admitted
                             & (seen_count / n_live >= batch.target))
        batch = dataclasses.replace(batch, seen_count=seen_count, done=done)

        resolved = _resolve_comm(comm)
        fn = _batch_cov_fn(mesh, axis_name, sg.n_shards, sg.block,
                           max_rounds, resolved, bool(donate),
                           rec=recorder is not None)
        dyn_src, dyn_dst, dyn_mask = _dyn_or_empty(sg)
        args = (sg.bkt_src, sg.bkt_dst, sg.bkt_mask, dyn_src, dyn_dst,
                dyn_mask, sg.node_mask, sg.out_degree,
                *_shard_batch_args(sg, batch))
        ftail = () if isinstance(resolved, str) \
            else (jnp.int32(fault_round0),)
        ring = None
        if recorder is None:
            (seen, frontier, sent, source, admitted, done, rounds_l,
             seen_count, target, packed) = fn(*args, *ftail)
        else:
            n_words = int(batch.seen.shape[0])
            base_fn = _batch_cov_fn(mesh, axis_name, sg.n_shards, sg.block,
                                    max_rounds, resolved, False)
            ici = _rec_ici_round_bytes(
                ("batch", mesh, axis_name, sg.n_shards, sg.block, resolved,
                 n_words),
                lambda: (base_fn, (*args, *ftail), sg.n_shards))
            (seen, frontier, sent, source, admitted, done, rounds_l,
             seen_count, target, packed, ring) = fn(
                *args, recorder.init(), jnp.float32(ici), *ftail)
        t1 = time.perf_counter()
        n_pad = batch.seen.shape[1]
        nbytes = sum(int(getattr(leaf, "nbytes", 0))
                     for leaf in jax.tree_util.tree_leaves((packed, ring)))
        if ring is not None:
            packed, ring = jax.device_get((packed, ring))
        out = accum.unpack_batch_summary(packed, int(batch.seen.shape[0]))
        _record_comm_faults(resolved, out["rounds"], sg.n_shards,
                            round0=fault_round0)
        if ring is not None:
            out["flight_record"] = flightrec.trim(ring, out["rounds"])
        batch = dataclasses.replace(
            batch,
            seen=unshard_lanes(sg, seen, n_pad),
            frontier=unshard_lanes(sg, frontier, n_pad),
            sent=unshard_lanes(sg, sent, n_pad),
            source=source, admitted=admitted, done=done, rounds=rounds_l,
            seen_count=seen_count, target=target,
        )
        t2 = time.perf_counter()
        newly = out["lane_done"] & ~done0
        # Engine-contract parity: the lanes completed in THIS call (the
        # serving front-end's harvest set) ride the summary here too.
        out["newly_completed_lanes"] = np.flatnonzero(newly).astype(np.int32)
        newly_rounds = out["lane_rounds"][newly]
        if newly_rounds.size:
            out["completion_rounds_p50"] = float(
                np.percentile(newly_rounds, 50))
            out["completion_rounds_p99"] = float(
                np.percentile(newly_rounds, 99))
        if tracer is not None:
            _engine._emit_batch_exit_events(admitted0, done0, out)
        # One summary-bridging site (engine's): shared sim_* counters under
        # loop="batch", batch gauges/histograms, occupancy recency pruning.
        _engine._record_batch_summary(t2 - t0, t2 - t1, nbytes, out,
                                      newly_rounds, type(protocol).__name__)
    return batch, out
