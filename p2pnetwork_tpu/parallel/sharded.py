"""Sharded graph propagation: ring ``ppermute`` over a device mesh.

This is the TPU-native replacement for the reference's only scaling story
(one OS thread per peer, O(E) sequential socket sends, SURVEY.md section
2.4). Design (SURVEY.md sections 5 "long-context" and 7 step 4):

- **Node-partitioned state**: node ``v`` lives on shard ``v // block``;
  per-node arrays (seen flags, values, statuses) are sharded on their
  leading axis.
- **Edge-partitioned adjacency, bucketed by source shard**: shard ``d``
  holds every edge whose *receiver* it owns, grouped into ``S`` buckets by
  the *sender*'s shard, ordered by ring distance (bucket ``t`` holds edges
  from shard ``(d - t) mod S``).
- **Ring exchange**: one propagation round runs ``S`` steps. At step ``t``
  each shard holds the frontier block of shard ``(d - t) mod S`` (rotated by
  ``lax.ppermute`` each step — neighbor traffic over ICI, the ring-attention
  communication shape) and applies exactly the edge bucket that consumes it.
  After ``S`` steps every cross-shard edge has been resolved with no
  all-gather and no DCN hot spot; per-round stats come back via ``psum``.

The whole multi-round propagation (scan over rounds, ring scan inside) is
one ``shard_map``-ped, jitted XLA program — zero host round-trips.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pnetwork_tpu.parallel.mesh import DEFAULT_AXIS, ring_mesh
from p2pnetwork_tpu.sim.graph import Graph, _round_up


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedGraph:
    """A :class:`Graph` partitioned for an ``S``-shard ring.

    ``bkt_*`` have global shape ``[S, S, E_bkt]`` — leading axis sharded
    (one row per destination shard), second axis the ring step. Local edge
    indices: ``bkt_src`` into the *rotating* frontier block, ``bkt_dst`` into
    the shard's own node block. Within a bucket, edges are sorted by
    destination so segment reductions see sorted ids.
    """

    bkt_src: jax.Array  # i32[S, S, E_bkt]
    bkt_dst: jax.Array  # i32[S, S, E_bkt]
    bkt_mask: jax.Array  # bool[S, S, E_bkt]
    node_mask: jax.Array  # bool[S, B]
    out_degree: jax.Array  # i32[S, B]
    n_nodes: int = dataclasses.field(metadata=dict(static=True))
    n_shards: int = dataclasses.field(metadata=dict(static=True))
    block: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_nodes_padded(self) -> int:
        return self.n_shards * self.block


def shard_graph(graph: Graph, mesh: Mesh, axis_name: str = DEFAULT_AXIS,
                edge_pad_multiple: int = 128) -> ShardedGraph:
    """Partition ``graph`` for ``mesh`` (host-side; one-off setup).

    Nodes are split into ``S`` contiguous blocks. Every active edge lands in
    bucket ``(dst_shard, ring_step)`` where ``ring_step = (dst_shard -
    src_shard) mod S`` — the step of the ring rotation at which the sender's
    frontier block is resident on the receiver's shard.
    """
    S = mesh.shape[axis_name]
    emask = np.asarray(graph.edge_mask)
    senders = np.asarray(graph.senders)[emask]
    receivers = np.asarray(graph.receivers)[emask]

    block = _round_up(graph.n_nodes_padded, S) // S
    src_shard = senders // block
    dst_shard = receivers // block
    step = (dst_shard - src_shard) % S

    # Bucket sizes -> common padded width.
    flat = dst_shard * S + step
    counts = np.bincount(flat, minlength=S * S)
    e_bkt = _round_up(max(int(counts.max()), 1), edge_pad_multiple)

    bkt_src = np.zeros((S, S, e_bkt), dtype=np.int32)
    # Pad destinations with block-1 so each bucket stays dst-sorted — the
    # segment reductions in the ring body promise indices_are_sorted=True.
    bkt_dst = np.full((S, S, e_bkt), block - 1, dtype=np.int32)
    bkt_mask = np.zeros((S, S, e_bkt), dtype=bool)

    # Sort edges by (bucket, local dst) so each bucket is dst-sorted.
    order = np.lexsort((receivers, flat))
    senders, receivers, flat = senders[order], receivers[order], flat[order]
    offsets = np.zeros(S * S + 1, dtype=np.int64)
    np.cumsum(np.bincount(flat, minlength=S * S), out=offsets[1:])
    for d in range(S):
        for t in range(S):
            b = d * S + t
            lo, hi = offsets[b], offsets[b + 1]
            n = hi - lo
            bkt_src[d, t, :n] = senders[lo:hi] % block
            bkt_dst[d, t, :n] = receivers[lo:hi] % block
            bkt_mask[d, t, :n] = True

    node_mask = np.asarray(graph.node_mask)
    node_mask = np.pad(node_mask, (0, S * block - node_mask.shape[0]))
    out_degree = np.asarray(graph.out_degree)
    out_degree = np.pad(out_degree, (0, S * block - out_degree.shape[0]))

    shard = NamedSharding(mesh, P(axis_name))
    dev = lambda x: jax.device_put(x, shard)  # noqa: E731
    return ShardedGraph(
        bkt_src=dev(bkt_src),
        bkt_dst=dev(bkt_dst),
        bkt_mask=dev(bkt_mask),
        node_mask=dev(node_mask.reshape(S, block)),
        out_degree=dev(out_degree.reshape(S, block).astype(np.int32)),
        n_nodes=graph.n_nodes,
        n_shards=S,
        block=block,
    )


def _ring_perm(S: int):
    """Send block to the next shard: after t applications, shard d holds the
    block originally on shard (d - t) mod S."""
    return [(i, (i + 1) % S) for i in range(S)]


def _ring_pass(axis_name, S, frontier, buckets, apply_bucket, acc0, combine):
    """One full ring rotation: apply bucket ``t`` to the block resident at
    ring step ``t``, folding results with ``combine``.

    The last bucket is peeled out of the scan: after it is applied there is
    nothing left to rotate, so running its ppermute would be one wasted ICI
    collective per pass.
    """
    bkt_src, bkt_dst, bkt_mask = buckets

    def ring_step(rc, bkt):
        rot, acc = rc  # rot: frontier block resident this step
        acc = combine(acc, apply_bucket(rot, *bkt))
        rot = jax.lax.ppermute(rot, axis_name, perm=_ring_perm(S))
        return (rot, acc), None

    if S > 1:
        (rot, acc), _ = jax.lax.scan(
            ring_step,
            (frontier, acc0),
            (bkt_src[: S - 1], bkt_dst[: S - 1], bkt_mask[: S - 1]),
        )
    else:
        rot, acc = frontier, acc0
    return combine(acc, apply_bucket(rot, bkt_src[S - 1], bkt_dst[S - 1],
                                     bkt_mask[S - 1]))


def _bucket_or(block):
    def apply(rot, src, dst, m):
        contrib = (rot[src] & m).astype(jnp.int32)
        return jax.ops.segment_max(
            contrib, dst, num_segments=block, indices_are_sorted=True
        ) > 0

    return apply


def _bucket_sum(block):
    def apply(rot, src, dst, m):
        contrib = rot[src] * m
        return jax.ops.segment_sum(
            contrib, dst, num_segments=block, indices_are_sorted=True
        )

    return apply


def _ring_rounds_or(axis_name, S, block, bkt_src, bkt_dst, bkt_mask,
                    node_mask, out_degree, seen0, frontier0, rounds):
    """Per-shard body (runs under shard_map): ``rounds`` flood rounds, each a
    full ring pass. All blocks carry a leading length-1 shard axis."""
    buckets = (bkt_src[0], bkt_dst[0], bkt_mask[0])
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    apply_bucket = _bucket_or(block)

    def one_round(carry, _):
        seen, frontier = carry  # [block] bool each
        delivered = _ring_pass(axis_name, S, frontier, buckets, apply_bucket,
                               jnp.zeros_like(seen), jnp.logical_or)
        new = delivered & ~seen & node_mask_b
        seen = seen | new
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        covered = jax.lax.psum(jnp.sum(seen.astype(jnp.int32)), axis_name)
        return (seen, new), {"messages": msgs, "covered": covered}

    (seen, frontier), stats = jax.lax.scan(
        one_round, (seen0[0], frontier0[0]), None, length=rounds
    )
    return seen[None], frontier[None], stats


@functools.lru_cache(maxsize=64)
def _flood_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int):
    """Build (and cache) the compiled sharded flood program for this shape."""
    body = functools.partial(_ring_rounds_or, axis_name, S, block)
    spec = P(axis_name)
    fn = jax.shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def flood(sg: ShardedGraph, mesh: Mesh, source: int, rounds: int,
          axis_name: str = DEFAULT_AXIS):
    """Run ``rounds`` of single-source flood on the sharded graph.

    Returns ``(seen [S, block] bool, stats dict of [rounds] arrays)`` — the
    sharded equivalent of ``engine.run(graph, Flood(source), ...)``, and
    bit-identical to it (tests/test_sharded.py).
    """
    S, block = sg.n_shards, sg.block
    seen0 = jnp.zeros((S, block), dtype=bool).at[source // block, source % block].set(True)
    frontier0 = seen0

    fn = _flood_fn(mesh, axis_name, S, block, rounds)
    seen, frontier, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, sg.node_mask, sg.out_degree,
        seen0, frontier0,
    )
    n_real = max(sg.n_nodes, 1)
    stats = {
        "messages": stats["messages"],
        "coverage": stats["covered"].astype(jnp.float32) / n_real,
    }
    return seen, stats


def _ring_rounds_sir(axis_name, S, block, exact_rng,
                     bkt_src, bkt_dst, bkt_mask, node_mask, out_degree,
                     status0, round_keys, one_minus_beta, gamma, rounds):
    """Per-shard body: ``rounds`` SIR rounds, infection pressure via a ring
    sum pass. ``round_keys`` is replicated raw key data [rounds, ...];
    ``beta``/``gamma`` are replicated scalars (runtime operands, so a
    parameter sweep does not recompile per value).

    ``exact_rng=True`` draws the full population's uniforms on every shard
    and slices out this shard's block — O(N) per shard, but bit-identical to
    the single-device engine (verification mode). ``exact_rng=False`` folds
    the shard index into the key — O(block), the scalable default.
    """
    from p2pnetwork_tpu.models.sir import INFECTED, RECOVERED, SUSCEPTIBLE

    buckets = (bkt_src[0], bkt_dst[0], bkt_mask[0])
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]
    apply_bucket = _bucket_sum(block)
    my = jax.lax.axis_index(axis_name)

    def draw(key, shape_full):
        if exact_rng:
            full = jax.random.uniform(key, (shape_full,))
            return jax.lax.dynamic_slice(full, (my * block,), (block,))
        return jax.random.uniform(jax.random.fold_in(key, my), (block,))

    def one_round(status, rkey):
        key = jax.random.wrap_key_data(rkey)
        k_inf, k_rec = jax.random.split(key)
        infected = (status == INFECTED) & node_mask_b
        susceptible = (status == SUSCEPTIBLE) & node_mask_b

        # pcast: a fresh constant is shard-invariant by type; the ring pass
        # folds shard-varying blocks into it, so the accumulator must be
        # marked varying up front (scan carries demand matching vma types).
        acc0 = jax.lax.pcast(
            jnp.zeros((block,), jnp.float32), (axis_name,), to="varying"
        )
        pressure = _ring_pass(
            axis_name, S, infected.astype(jnp.float32), buckets, apply_bucket,
            acc0, jnp.add,
        )
        # one_minus_beta arrives precomputed in f64 then cast, matching the
        # engine's `jnp.power(1.0 - beta, ...)` constant bit-for-bit.
        p_infect = 1.0 - jnp.power(one_minus_beta, pressure)
        newly_infected = susceptible & (draw(k_inf, S * block) < p_infect)
        recovers = infected & (draw(k_rec, S * block) < gamma)

        status = jnp.where(newly_infected, INFECTED, status)
        status = jnp.where(recovers, RECOVERED, status)

        def frac(mask):
            return jax.lax.psum(jnp.sum(mask.astype(jnp.int32)), axis_name)

        stats = {
            "messages": jax.lax.psum(
                jnp.sum(jnp.where(infected, out_degree_b, 0)), axis_name
            ),
            "s": frac((status == SUSCEPTIBLE) & node_mask_b),
            "i": frac((status == INFECTED) & node_mask_b),
            "r": frac((status == RECOVERED) & node_mask_b),
        }
        return status, stats

    status, stats = jax.lax.scan(one_round, status0[0], round_keys)
    return status[None], stats


@functools.lru_cache(maxsize=64)
def _sir_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int,
            exact_rng: bool):
    body = functools.partial(_ring_rounds_sir, axis_name, S, block, exact_rng)
    spec = P(axis_name)
    fn = jax.shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh,
        in_specs=(spec,) * 6 + (P(), P(), P()),
        out_specs=(spec, P()),
    )
    return jax.jit(fn)


def sir(sg: ShardedGraph, mesh: Mesh, protocol, key: jax.Array, rounds: int,
        axis_name: str = DEFAULT_AXIS, exact_rng: bool = False):
    """Run ``rounds`` of SIR (models/sir.py) on the sharded graph.

    Returns ``(status [S, block] i32, stats dict of [rounds] arrays)``. The
    key schedule matches ``engine.run``'s, so with ``exact_rng=True`` and a
    node count divisible by the shard count this is bit-identical to the
    single-device engine (tests/test_sharded.py).
    """
    S, block = sg.n_shards, sg.block
    source = protocol.source
    status0 = (
        jnp.zeros((S, block), dtype=jnp.int32)
        .at[source // block, source % block].set(1)
    )
    # engine.run's schedule: one subkey per round off fold_in(key, 1).
    round_keys = jax.random.key_data(
        jax.random.split(jax.random.fold_in(key, 1), rounds)
    )
    fn = _sir_fn(mesh, axis_name, S, block, rounds, bool(exact_rng))
    status, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, sg.node_mask, sg.out_degree,
        status0, round_keys,
        jnp.float32(1.0 - protocol.beta), jnp.float32(protocol.gamma),
    )
    n_real = max(sg.n_nodes, 1)
    return status, {
        "messages": stats["messages"],
        "s_frac": stats["s"].astype(jnp.float32) / n_real,
        "i_frac": stats["i"].astype(jnp.float32) / n_real,
        "r_frac": stats["r"].astype(jnp.float32) / n_real,
        "coverage": (n_real - stats["s"]).astype(jnp.float32) / n_real,
    }
