"""Sharded graph propagation: ring ``ppermute`` over a device mesh.

This is the TPU-native replacement for the reference's only scaling story
(one OS thread per peer, O(E) sequential socket sends, SURVEY.md section
2.4). Design (SURVEY.md sections 5 "long-context" and 7 step 4):

- **Node-partitioned state**: node ``v`` lives on shard ``v // block``;
  per-node arrays (seen flags, values, statuses) are sharded on their
  leading axis.
- **Edge-partitioned adjacency, bucketed by source shard**: shard ``d``
  holds every edge whose *receiver* it owns, grouped into ``S`` buckets by
  the *sender*'s shard, ordered by ring distance (bucket ``t`` holds edges
  from shard ``(d - t) mod S``).
- **Ring exchange**: one propagation round runs ``S`` steps. At step ``t``
  each shard holds the frontier block of shard ``(d - t) mod S`` (rotated by
  ``lax.ppermute`` each step — neighbor traffic over ICI, the ring-attention
  communication shape) and applies exactly the edge bucket that consumes it.
  After ``S`` steps every cross-shard edge has been resolved with no
  all-gather and no DCN hot spot; per-round stats come back via ``psum``.

The whole multi-round propagation (scan over rounds, ring scan inside) is
one ``shard_map``-ped, jitted XLA program — zero host round-trips.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pnetwork_tpu.parallel.mesh import DEFAULT_AXIS, ring_mesh
from p2pnetwork_tpu.sim.graph import Graph, _round_up


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ShardedGraph:
    """A :class:`Graph` partitioned for an ``S``-shard ring.

    ``bkt_*`` have global shape ``[S, S, E_bkt]`` — leading axis sharded
    (one row per destination shard), second axis the ring step. Local edge
    indices: ``bkt_src`` into the *rotating* frontier block, ``bkt_dst`` into
    the shard's own node block. Within a bucket, edges are sorted by
    destination so segment reductions see sorted ids.
    """

    bkt_src: jax.Array  # i32[S, S, E_bkt]
    bkt_dst: jax.Array  # i32[S, S, E_bkt]
    bkt_mask: jax.Array  # bool[S, S, E_bkt]
    node_mask: jax.Array  # bool[S, B]
    out_degree: jax.Array  # i32[S, B]
    n_nodes: int = dataclasses.field(metadata=dict(static=True))
    n_shards: int = dataclasses.field(metadata=dict(static=True))
    block: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_nodes_padded(self) -> int:
        return self.n_shards * self.block


def shard_graph(graph: Graph, mesh: Mesh, axis_name: str = DEFAULT_AXIS,
                edge_pad_multiple: int = 128) -> ShardedGraph:
    """Partition ``graph`` for ``mesh`` (host-side; one-off setup).

    Nodes are split into ``S`` contiguous blocks. Every active edge lands in
    bucket ``(dst_shard, ring_step)`` where ``ring_step = (dst_shard -
    src_shard) mod S`` — the step of the ring rotation at which the sender's
    frontier block is resident on the receiver's shard.
    """
    S = mesh.shape[axis_name]
    emask = np.asarray(graph.edge_mask)
    senders = np.asarray(graph.senders)[emask]
    receivers = np.asarray(graph.receivers)[emask]

    block = _round_up(graph.n_nodes_padded, S) // S
    src_shard = senders // block
    dst_shard = receivers // block
    step = (dst_shard - src_shard) % S

    # Bucket sizes -> common padded width.
    flat = dst_shard * S + step
    counts = np.bincount(flat, minlength=S * S)
    e_bkt = _round_up(max(int(counts.max()), 1), edge_pad_multiple)

    bkt_src = np.zeros((S, S, e_bkt), dtype=np.int32)
    # Pad destinations with block-1 so each bucket stays dst-sorted — the
    # segment reductions in the ring body promise indices_are_sorted=True.
    bkt_dst = np.full((S, S, e_bkt), block - 1, dtype=np.int32)
    bkt_mask = np.zeros((S, S, e_bkt), dtype=bool)

    # Sort edges by (bucket, local dst) so each bucket is dst-sorted.
    order = np.lexsort((receivers, flat))
    senders, receivers, flat = senders[order], receivers[order], flat[order]
    offsets = np.zeros(S * S + 1, dtype=np.int64)
    np.cumsum(np.bincount(flat, minlength=S * S), out=offsets[1:])
    for d in range(S):
        for t in range(S):
            b = d * S + t
            lo, hi = offsets[b], offsets[b + 1]
            n = hi - lo
            bkt_src[d, t, :n] = senders[lo:hi] % block
            bkt_dst[d, t, :n] = receivers[lo:hi] % block
            bkt_mask[d, t, :n] = True

    node_mask = np.asarray(graph.node_mask)
    node_mask = np.pad(node_mask, (0, S * block - node_mask.shape[0]))
    out_degree = np.asarray(graph.out_degree)
    out_degree = np.pad(out_degree, (0, S * block - out_degree.shape[0]))

    shard = NamedSharding(mesh, P(axis_name))
    dev = lambda x: jax.device_put(x, shard)  # noqa: E731
    return ShardedGraph(
        bkt_src=dev(bkt_src),
        bkt_dst=dev(bkt_dst),
        bkt_mask=dev(bkt_mask),
        node_mask=dev(node_mask.reshape(S, block)),
        out_degree=dev(out_degree.reshape(S, block).astype(np.int32)),
        n_nodes=graph.n_nodes,
        n_shards=S,
        block=block,
    )


def _ring_perm(S: int):
    """Send block to the next shard: after t applications, shard d holds the
    block originally on shard (d - t) mod S."""
    return [(i, (i + 1) % S) for i in range(S)]


def _ring_rounds_or(axis_name, S, block, bkt_src, bkt_dst, bkt_mask,
                    node_mask, out_degree, seen0, frontier0, rounds):
    """Per-shard body (runs under shard_map): ``rounds`` flood rounds, each a
    full ring pass. All blocks carry a leading length-1 shard axis."""
    bkt_src, bkt_dst, bkt_mask = bkt_src[0], bkt_dst[0], bkt_mask[0]
    node_mask_b, out_degree_b = node_mask[0], out_degree[0]

    def apply_bucket(rot, src, dst, m):
        contrib = (rot[src] & m).astype(jnp.int32)
        return jax.ops.segment_max(
            contrib, dst, num_segments=block, indices_are_sorted=True
        ) > 0

    def one_round(carry, _):
        seen, frontier = carry  # [block] bool each

        def ring_step(rc, bkt):
            rot, acc = rc  # rot: frontier block resident this step
            acc = acc | apply_bucket(rot, *bkt)
            rot = jax.lax.ppermute(rot, axis_name, perm=_ring_perm(S))
            return (rot, acc), None

        # The last bucket is peeled out of the scan: after it is applied
        # there is nothing left to rotate, so running its ppermute would be
        # one wasted ICI collective per round.
        if S > 1:
            (rot, delivered), _ = jax.lax.scan(
                ring_step,
                (frontier, jnp.zeros_like(seen)),
                (bkt_src[: S - 1], bkt_dst[: S - 1], bkt_mask[: S - 1]),
            )
        else:
            rot, delivered = frontier, jnp.zeros_like(seen)
        delivered = delivered | apply_bucket(
            rot, bkt_src[S - 1], bkt_dst[S - 1], bkt_mask[S - 1]
        )
        new = delivered & ~seen & node_mask_b
        seen = seen | new
        msgs = jax.lax.psum(
            jnp.sum(jnp.where(frontier, out_degree_b, 0)), axis_name
        )
        covered = jax.lax.psum(jnp.sum(seen.astype(jnp.int32)), axis_name)
        return (seen, new), {"messages": msgs, "covered": covered}

    (seen, frontier), stats = jax.lax.scan(
        one_round, (seen0[0], frontier0[0]), None, length=rounds
    )
    return seen[None], frontier[None], stats


@functools.lru_cache(maxsize=64)
def _flood_fn(mesh: Mesh, axis_name: str, S: int, block: int, rounds: int):
    """Build (and cache) the compiled sharded flood program for this shape."""
    body = functools.partial(_ring_rounds_or, axis_name, S, block)
    spec = P(axis_name)
    fn = jax.shard_map(
        lambda *args: body(*args, rounds=rounds),
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, spec, P()),
    )
    return jax.jit(fn)


def flood(sg: ShardedGraph, mesh: Mesh, source: int, rounds: int,
          axis_name: str = DEFAULT_AXIS):
    """Run ``rounds`` of single-source flood on the sharded graph.

    Returns ``(seen [S, block] bool, stats dict of [rounds] arrays)`` — the
    sharded equivalent of ``engine.run(graph, Flood(source), ...)``, and
    bit-identical to it (tests/test_sharded.py).
    """
    S, block = sg.n_shards, sg.block
    seen0 = jnp.zeros((S, block), dtype=bool).at[source // block, source % block].set(True)
    frontier0 = seen0

    fn = _flood_fn(mesh, axis_name, S, block, rounds)
    seen, frontier, stats = fn(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, sg.node_mask, sg.out_degree,
        seen0, frontier0,
    )
    n_real = max(sg.n_nodes, 1)
    stats = {
        "messages": stats["messages"],
        "coverage": stats["covered"].astype(jnp.float32) / n_real,
    }
    return seen, stats
