"""Collective-placement diagnostics: parse compiled HLO, classify traffic.

The multi-chip claims this framework makes — node-extent-only payloads on
the GSPMD auto path, ICI-confined bulk traffic with DCN as a bounded
remainder, the ring's 1/per_host boundary-hop structure — are properties
of COMPILED programs, so the evidence lives in HLO text. This module is
the one parser both the test suite (tests/test_auto_comm.py,
tests/test_mesh2d_comm.py) and the shipped diagnostics/examples
(examples/hierarchical_mesh_demo.py) use, so the pinned assertions and
the printed numbers cannot drift apart.

Handles XLA's iota replica-group form (``[G,S]<=[dims]T(perm)``), the
literal form (``{{0,1},{2,3}}``), variadic/async collectives, and
collective-permutes (which carry ``source_target_pairs`` instead of
replica groups — skipping them would blind any DCN budget to cross-host
permute traffic).

The reference has nothing comparable to diagnose — its transport is one
blocking socket per peer [ref: p2pnetwork/nodeconnection.py:38-44].
"""

from __future__ import annotations

import re
from typing import Callable, List, Tuple

import numpy as np

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
                "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
                "u64": 8}

# Matches the full (possibly tuple/variadic) result type of a collective —
# XLA's collective combiner fuses ops into variadic forms like
#   (s32[], s32[], f32[4096]) all-reduce(...)
# and async pairs use the -start suffix; both must stay visible here or an
# edge-extent payload could hide inside a fused/async op.
COLLECTIVE_LINE = re.compile(
    r"=\s+(.+?)\s+"
    r"(all-gather|all-reduce|all-to-all|collective-permute|reduce-scatter)"
    r"(?:-start)?\("
)
_SHAPE = re.compile(r"(\w+)\[([0-9,]*)\]")
_IOTA = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_LITERAL = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_PAIRS = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")


def collectives(hlo_text: str) -> List[Tuple[str, str, tuple, int]]:
    """``[(op, dtype, shape, bytes)]`` — one entry per tensor component of
    every collective in the module, tuple results flattened."""
    out = []
    for type_str, op in COLLECTIVE_LINE.findall(hlo_text):
        for dtype, shape in _SHAPE.findall(type_str):
            if dtype not in _DTYPE_BYTES:
                continue  # e.g. token types
            dims = [int(d) for d in shape.split(",") if d] or [1]  # graftlint: ignore[host-sync-in-loop] -- regex capture strings, not jax arrays
            out.append((op, dtype, tuple(dims),
                        int(np.prod(dims)) * _DTYPE_BYTES[dtype]))  # graftlint: ignore[host-sync-in-loop] -- host ints from parsed HLO text
    return out


def decode_groups(line: str) -> List[tuple]:
    """Replica groups of one HLO collective line as a list of tuples."""
    m = _IOTA.search(line)
    if m:
        ng, gs = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        perm = ([int(d) for d in m.group(4).split(",")]
                if m.group(4) else list(range(len(dims))))
        devs = np.arange(int(np.prod(dims))).reshape(dims).transpose(perm)
        return [tuple(int(x) for x in g) for g in devs.reshape(ng, gs)]
    m = _LITERAL.search(line)
    if m:
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in m.group(1).strip("{}").split("},{")]
    return []


def permute_pairs(line: str) -> List[Tuple[int, int]]:
    """source->target pairs of one collective-permute HLO line."""
    m = _PAIRS.search(line)
    if not m:
        return []
    return [tuple(int(x) for x in p.split(","))
            for p in m.group(1).strip("{}").split("},{")]


def classify_collective_bytes(hlo: str,
                              host_of: Callable[[int], int]) -> Tuple[int, int]:
    """``(within_host_bytes, cross_host_bytes)`` over every collective in
    the module — replica-group collectives classified by decoded groups,
    collective-permutes by their source->target pairs. ``host_of`` maps a
    linearized device id to its host/slice index."""
    within = cross = 0
    for ln in hlo.splitlines():
        if not COLLECTIVE_LINE.search(ln):
            continue
        groups = decode_groups(ln)
        pairs = permute_pairs(ln)
        if not groups and not pairs:
            continue
        nbytes = sum(c[3] for c in collectives(ln))
        crossing = (any(len({host_of(d) for d in g}) > 1 for g in groups)
                    or any(host_of(a) != host_of(b) for a, b in pairs))
        if crossing:
            cross += nbytes
        else:
            within += nbytes
    return within, cross


def record_traffic(hlo: str, host_of: Callable[[int], int], *,
                   program: str = "default",
                   registry=None) -> Tuple[int, int]:
    """Classify ``hlo``'s collective traffic and publish it as gauges in
    the telemetry registry: ``comm_collective_bytes{program, placement}``
    with ``placement="within_host"`` (ICI-confined on a TPU slice) and
    ``"cross_host"`` (the DCN remainder). Returns the same
    ``(within, cross)`` tuple as :func:`classify_collective_bytes`, so
    diagnostics can keep their printed numbers and the registry's budget
    gauges from drifting apart — one classification, two consumers."""
    from p2pnetwork_tpu import telemetry

    within, cross = classify_collective_bytes(hlo, host_of)
    reg = registry or telemetry.default_registry()
    g = reg.gauge(
        "comm_collective_bytes",
        "Collective payload bytes of a compiled program by interconnect "
        "placement (within_host ~ ICI budget, cross_host ~ DCN budget).",
        ("program", "placement"))
    g.labels(program, "within_host").set(within)
    g.labels(program, "cross_host").set(cross)
    return within, cross


# --------------------------------------------------- Pallas ring-DMA census
#
# The pallas comm backend (ops/pallas_ring.py) moves the halo as
# ``make_async_remote_copy`` DMAs issued from inside kernels. Those are
# INVISIBLE to both censuses above: the jaxpr shows one opaque
# ``pallas_call`` eqn (no ppermute), and the interpret-mode CPU lowering
# compiles to callbacks (no collective-permute in HLO) — so without this
# section a Pallas-comm program would read as zero ICI bytes and silently
# pass every comm budget. The handle is the kernel NAME: every ring-DMA
# kernel is named ``ring_halo_*`` (pallas_ring.RING_DMA_MARKER), the name
# lands in the pallas_call eqn's ``name_and_src_info``, and by convention
# the kernel's FIRST output is the DMA payload (the received block), so
# ``outvars[0]`` prices the hop — one payload copy per hop, the same
# model a ppermute is priced at.

#: Substring marking a ring-DMA kernel's pallas_call (kept in lockstep
#: with ops/pallas_ring.RING_DMA_MARKER — pinned by tests/test_ring.py;
#: duplicated here so this module stays importable without jax/pallas).
RING_DMA_MARKER = "ring_halo"

#: The jaxpr-level pseudo-collective key ring DMAs are censused under
#: (beside ppermute/psum/... in graftaudit's collective census).
RING_DMA_KEY = "ring_dma"


def ring_model_bytes(prim: str, nbytes: int, axis_size: int) -> int:
    """The documented static ICI byte model of one collective occurrence
    on an ``axis_size``-way ring: ppermute — and a ring-DMA hop — moves
    each operand once; psum (ring all-reduce) moves ``2·(S-1)/S ≈ 2``
    copies; all_gather moves ``S-1`` shard-sized pieces. One model, two
    consumers: graftaudit's jaxpr census ratchet
    (analysis/ir/registry.py) and the comm estimates below."""
    s = max(axis_size, 2)
    if prim in ("ppermute", RING_DMA_KEY):
        return nbytes
    if prim in ("psum", "pmax", "pmin"):
        return int(nbytes * 2 * (s - 1) / s)
    if prim in ("all_gather", "all_to_all", "reduce_scatter"):
        return nbytes * (s - 1)
    return nbytes


def ring_dma_payload_bytes(eqn) -> int:
    """DMA payload bytes of one jaxpr eqn: the first output's extent when
    the eqn is a ring-DMA ``pallas_call`` (see RING_DMA_MARKER), else 0.
    Takes a ``jax.core.JaxprEqn`` — jax is imported by the caller."""
    if eqn.primitive.name != "pallas_call":
        return 0
    name = str(eqn.params.get("name_and_src_info", "")) or str(
        eqn.params.get("name", ""))
    if RING_DMA_MARKER not in name:
        return 0
    aval = eqn.outvars[0].aval
    import numpy as _np

    return int(_np.prod(aval.shape, dtype=_np.int64) or 1) * aval.dtype.itemsize


def jaxpr_comm_census(fn, args, axis_size: int) -> dict:
    """Trace ``fn(*args)`` abstractly and census its cross-device traffic
    under the ring byte model: ``{prim: {"count", "bytes"}}`` over every
    collective primitive PLUS ``"ring_dma"`` for Pallas ring-DMA kernels
    — the estimate the bench ``multichip`` column and the comm-budget
    tests read for BOTH comm backends of the sharded path.

    Counts are weighted by statically-known trip counts: a collective
    inside a ``lax.scan`` / ``fori`` body is multiplied by the scan
    length — the ring pass is a length-``S-1`` scan of one hop, so a
    ring program's totals price all ``S-1`` hops per pass, not one.
    ``while_loop`` bodies (trip count dynamic) count once, so on the
    run-to-* loops the totals are PER-ROUND bytes."""
    import jax

    from p2pnetwork_tpu.analysis.ir.registry import COLLECTIVE_PRIMS

    closed = jax.make_jaxpr(fn)(*args)
    out: dict = {}

    def bump(key, nbytes, times):
        rec = out.setdefault(key, {"count": 0, "bytes": 0})
        rec["count"] += times
        rec["bytes"] += nbytes * times

    def visit(jaxpr, times):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim in COLLECTIVE_PRIMS:
                nbytes = sum(  # graftlint: ignore[host-sync-in-loop] -- aval shapes are host ints (abstract trace), no device values
                    int(np.prod(v.aval.shape, dtype=np.int64) or 1)
                    * v.aval.dtype.itemsize
                    for v in eqn.invars if hasattr(v, "aval"))
                bump(prim, ring_model_bytes(prim, nbytes, axis_size), times)
            else:
                payload = ring_dma_payload_bytes(eqn)
                if payload:
                    bump(RING_DMA_KEY,
                         ring_model_bytes(RING_DMA_KEY, payload, axis_size),
                         times)
            inner_times = times
            if prim == "scan":
                inner_times = times * int(eqn.params.get("length", 1))  # graftlint: ignore[host-sync-in-loop] -- scan length is a static Python int in jaxpr params
            for v in eqn.params.values():
                for x in (v if isinstance(v, (tuple, list)) else (v,)):
                    if hasattr(x, "eqns"):
                        visit(x, inner_times)
                    elif hasattr(getattr(x, "jaxpr", None), "eqns"):
                        visit(x.jaxpr, inner_times)

    visit(closed.jaxpr, 1)
    return out


def ici_bytes_estimate(fn, args, axis_size: int) -> int:
    """Total modeled ICI bytes of one traced program (collectives + ring
    DMAs) — the single number comm-budget assertions compare across the
    ppermute and pallas backends of the same ring program."""
    return sum(rec["bytes"]
               for rec in jaxpr_comm_census(fn, args, axis_size).values())


def ring_hop_classes(hlo: str, host_of: Callable[[int], int]):
    """``(within_hops, cross_hops, permute_pair_lists)`` over every
    collective-permute of a compiled ring program."""
    within = cross = 0
    per_permute = []
    for ln in hlo.splitlines():
        if "collective-permute" not in ln:
            continue
        pairs = permute_pairs(ln)
        if not pairs:
            continue
        per_permute.append(pairs)
        for a, b in pairs:
            if host_of(a) == host_of(b):
                within += 1
            else:
                cross += 1
    return within, cross, per_permute


def lower_ring_flood_hlo(n: int = 1024, n_devices: int = 8,
                         rounds: int = 3, comm: str = "ppermute") -> str:
    """Compile the real sharded ring flood over an ``n_devices`` ring mesh
    and return its HLO text — the program whose hop placement
    :func:`ring_hop_classes` reads. ``comm`` selects the halo backend;
    note the pallas backend's DMA hops do NOT appear as HLO collectives
    (use :func:`jaxpr_comm_census` for backend-comparable byte
    estimates)."""
    from p2pnetwork_tpu.parallel import mesh as M, sharded
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(n, 6, 0.2, seed=0)
    mesh = M.ring_mesh(n_devices)
    sg = sharded.shard_graph(g, mesh)
    fn = sharded._flood_fn(mesh, mesh.axis_names[0], sg.n_shards,
                           sg.block, rounds, sg.diag_pieces, sg.mxu_block,
                           comm)
    seen0 = sharded._flood_seed(sg, 0)
    return fn.lower(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, *sharded._dyn_or_empty(sg),
        *sharded._mxu_or_empty(sg), sharded._diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, seen0, seen0,
    ).compile().as_text()
