"""Collective-placement diagnostics: parse compiled HLO, classify traffic.

The multi-chip claims this framework makes — node-extent-only payloads on
the GSPMD auto path, ICI-confined bulk traffic with DCN as a bounded
remainder, the ring's 1/per_host boundary-hop structure — are properties
of COMPILED programs, so the evidence lives in HLO text. This module is
the one parser both the test suite (tests/test_auto_comm.py,
tests/test_mesh2d_comm.py) and the shipped diagnostics/examples
(examples/hierarchical_mesh_demo.py) use, so the pinned assertions and
the printed numbers cannot drift apart.

Handles XLA's iota replica-group form (``[G,S]<=[dims]T(perm)``), the
literal form (``{{0,1},{2,3}}``), variadic/async collectives, and
collective-permutes (which carry ``source_target_pairs`` instead of
replica groups — skipping them would blind any DCN budget to cross-host
permute traffic).

The reference has nothing comparable to diagnose — its transport is one
blocking socket per peer [ref: p2pnetwork/nodeconnection.py:38-44].
"""

from __future__ import annotations

import re
from typing import Callable, List, Tuple

import numpy as np

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
                "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
                "u64": 8}

# Matches the full (possibly tuple/variadic) result type of a collective —
# XLA's collective combiner fuses ops into variadic forms like
#   (s32[], s32[], f32[4096]) all-reduce(...)
# and async pairs use the -start suffix; both must stay visible here or an
# edge-extent payload could hide inside a fused/async op.
COLLECTIVE_LINE = re.compile(
    r"=\s+(.+?)\s+"
    r"(all-gather|all-reduce|all-to-all|collective-permute|reduce-scatter)"
    r"(?:-start)?\("
)
_SHAPE = re.compile(r"(\w+)\[([0-9,]*)\]")
_IOTA = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_LITERAL = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_PAIRS = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")


def collectives(hlo_text: str) -> List[Tuple[str, str, tuple, int]]:
    """``[(op, dtype, shape, bytes)]`` — one entry per tensor component of
    every collective in the module, tuple results flattened."""
    out = []
    for type_str, op in COLLECTIVE_LINE.findall(hlo_text):
        for dtype, shape in _SHAPE.findall(type_str):
            if dtype not in _DTYPE_BYTES:
                continue  # e.g. token types
            dims = [int(d) for d in shape.split(",") if d] or [1]
            out.append((op, dtype, tuple(dims),
                        int(np.prod(dims)) * _DTYPE_BYTES[dtype]))
    return out


def decode_groups(line: str) -> List[tuple]:
    """Replica groups of one HLO collective line as a list of tuples."""
    m = _IOTA.search(line)
    if m:
        ng, gs = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        perm = ([int(d) for d in m.group(4).split(",")]
                if m.group(4) else list(range(len(dims))))
        devs = np.arange(int(np.prod(dims))).reshape(dims).transpose(perm)
        return [tuple(int(x) for x in g) for g in devs.reshape(ng, gs)]
    m = _LITERAL.search(line)
    if m:
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in m.group(1).strip("{}").split("},{")]
    return []


def permute_pairs(line: str) -> List[Tuple[int, int]]:
    """source->target pairs of one collective-permute HLO line."""
    m = _PAIRS.search(line)
    if not m:
        return []
    return [tuple(int(x) for x in p.split(","))
            for p in m.group(1).strip("{}").split("},{")]


def classify_collective_bytes(hlo: str,
                              host_of: Callable[[int], int]) -> Tuple[int, int]:
    """``(within_host_bytes, cross_host_bytes)`` over every collective in
    the module — replica-group collectives classified by decoded groups,
    collective-permutes by their source->target pairs. ``host_of`` maps a
    linearized device id to its host/slice index."""
    within = cross = 0
    for ln in hlo.splitlines():
        if not COLLECTIVE_LINE.search(ln):
            continue
        groups = decode_groups(ln)
        pairs = permute_pairs(ln)
        if not groups and not pairs:
            continue
        nbytes = sum(c[3] for c in collectives(ln))
        crossing = (any(len({host_of(d) for d in g}) > 1 for g in groups)
                    or any(host_of(a) != host_of(b) for a, b in pairs))
        if crossing:
            cross += nbytes
        else:
            within += nbytes
    return within, cross


def record_traffic(hlo: str, host_of: Callable[[int], int], *,
                   program: str = "default",
                   registry=None) -> Tuple[int, int]:
    """Classify ``hlo``'s collective traffic and publish it as gauges in
    the telemetry registry: ``comm_collective_bytes{program, placement}``
    with ``placement="within_host"`` (ICI-confined on a TPU slice) and
    ``"cross_host"`` (the DCN remainder). Returns the same
    ``(within, cross)`` tuple as :func:`classify_collective_bytes`, so
    diagnostics can keep their printed numbers and the registry's budget
    gauges from drifting apart — one classification, two consumers."""
    from p2pnetwork_tpu import telemetry

    within, cross = classify_collective_bytes(hlo, host_of)
    reg = registry or telemetry.default_registry()
    g = reg.gauge(
        "comm_collective_bytes",
        "Collective payload bytes of a compiled program by interconnect "
        "placement (within_host ~ ICI budget, cross_host ~ DCN budget).",
        ("program", "placement"))
    g.labels(program, "within_host").set(within)
    g.labels(program, "cross_host").set(cross)
    return within, cross


def ring_hop_classes(hlo: str, host_of: Callable[[int], int]):
    """``(within_hops, cross_hops, permute_pair_lists)`` over every
    collective-permute of a compiled ring program."""
    within = cross = 0
    per_permute = []
    for ln in hlo.splitlines():
        if "collective-permute" not in ln:
            continue
        pairs = permute_pairs(ln)
        if not pairs:
            continue
        per_permute.append(pairs)
        for a, b in pairs:
            if host_of(a) == host_of(b):
                within += 1
            else:
                cross += 1
    return within, cross, per_permute


def lower_ring_flood_hlo(n: int = 1024, n_devices: int = 8,
                         rounds: int = 3) -> str:
    """Compile the real sharded ring flood over an ``n_devices`` ring mesh
    and return its HLO text — the program whose hop placement
    :func:`ring_hop_classes` reads."""
    from p2pnetwork_tpu.parallel import mesh as M, sharded
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(n, 6, 0.2, seed=0)
    mesh = M.ring_mesh(n_devices)
    sg = sharded.shard_graph(g, mesh)
    fn = sharded._flood_fn(mesh, mesh.axis_names[0], sg.n_shards,
                           sg.block, rounds, sg.diag_pieces, sg.mxu_block)
    seen0 = sharded._flood_seed(sg, 0)
    return fn.lower(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, *sharded._dyn_or_empty(sg),
        *sharded._mxu_or_empty(sg), sharded._diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, seen0, seen0,
    ).compile().as_text()
