"""Multi-device scale-out: ring meshes (mesh.py) and sharded graph
propagation with ppermute ring exchange (sharded.py)."""

from p2pnetwork_tpu.parallel.mesh import ring_mesh, shard_spec
from p2pnetwork_tpu.parallel.sharded import (CommPayloadMismatch,
                                              ShardedGraph, flood,
                                              shard_graph)

__all__ = ["ring_mesh", "shard_spec", "ShardedGraph", "shard_graph", "flood",
           "CommPayloadMismatch"]
