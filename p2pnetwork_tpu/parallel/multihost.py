"""Multi-host (DCN + ICI) mesh construction and process bootstrap.

The reference's multi-machine story is hand-configured TCP: every process
binds a host:port and users wire the topology by calling
``connect_with_node`` with literal addresses [ref: README.md:70-105,
examples/my_own_p2p_application.py]. The sim backend's story is JAX
multi-process: one process per host, ``jax.distributed`` for rendezvous,
and a device mesh spanning every chip in the job, with the slice-internal
axis riding ICI and the cross-host axis riding DCN.

The ring propagation in parallel/sharded.py is communication-shaped like
ring attention: each step talks only to ring neighbors. The win on a
multi-host job is therefore entirely in RING ORDER: lay the ring out
ICI-major (all of a host's chips are consecutive), and S-1 of every S ring
hops ride ICI; only the host-boundary hops cross DCN. That layout is what
:func:`hierarchical_ring_mesh` builds — the ring path needs no code
changes, just this device ordering.

For compiler-inserted collectives (parallel/auto.py) the conventional 2-D
mesh (:func:`mesh_2d`, axes ``("dcn", "ici")``) is provided: shard the
node axis over ``ici`` and replicate (or data-parallel) over ``dcn``, the
standard "never let a sharded matmul's collective cross DCN" recipe.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from p2pnetwork_tpu.parallel.mesh import DEFAULT_AXIS


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bootstrap ``jax.distributed`` for a multi-host job.

    Arguments fall back to the standard environment (JAX_COORDINATOR_ADDRESS
    / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or a TPU pod's built-in metadata —
    jax.distributed.initialize() with no arguments auto-detects on Cloud
    TPU). Returns True when running multi-process, False for the
    single-process case (no-op — every code path below works unchanged).
    """
    env = os.environ
    coordinator_address = coordinator_address or env.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and env.get("JAX_NUM_PROCESSES"):
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and env.get("JAX_PROCESS_ID"):
        process_id = int(env["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        if jax.process_count() > 1:
            return True  # already initialized (e.g. by the launcher)
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def _devices_host_major(devices: Optional[Sequence[jax.Device]] = None):
    """All job devices ordered host-major (every host's chips consecutive),
    host order by process index, chips by device id within a host."""
    devs = list(devices) if devices is not None else jax.devices()
    return sorted(devs, key=lambda d: (d.process_index, d.id))


def hierarchical_ring_mesh(
    axis_name: str = DEFAULT_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 1-D ring mesh over every device in the job, ICI-major.

    Drop-in for ``mesh.ring_mesh`` in a multi-host job: with hosts'
    chips consecutive on the ring, the sharded ring propagation crosses DCN
    only at host boundaries (chips_per_host - 1 of every chips_per_host
    hops stay on ICI).
    """
    devs = _devices_host_major(devices)
    return Mesh(np.array(devs), (axis_name,))


def mesh_2d(
    axis_names: tuple = ("dcn", "ici"),
    devices: Optional[Sequence[jax.Device]] = None,
    hosts: Optional[int] = None,
) -> Mesh:
    """A ``[hosts, chips_per_host]`` mesh: leading axis crosses DCN, trailing
    axis stays inside a host's ICI domain. For the auto-sharded path: put
    the node/edge axes on ``ici`` and keep ``dcn`` for replication or
    independent runs (parameter sweeps).

    ``hosts`` overrides the process-derived host count — the way a
    single-process virtual-device job emulates a multi-slice layout
    (e.g. 2x4 over 8 CPU devices) so the per-axis collective placement
    is testable without real DCN (tests/test_mesh2d_comm.py)."""
    devs = _devices_host_major(devices)
    n_hosts = (hosts if hosts is not None
               else max(len({d.process_index for d in devs}), 1))
    per_host = len(devs) // n_hosts
    if n_hosts * per_host != len(devs):
        raise ValueError(
            f"uneven device count: {len(devs)} devices over {n_hosts} hosts"
        )
    grid = np.array(devs).reshape(n_hosts, per_host)
    return Mesh(grid, axis_names)
