"""Live Vivaldi coordinates on the sockets backend — the two pillars met.

The sim backend learns latency embeddings over a WEIGHTED GRAPH
(models/vivaldi.py); this module runs the same spring rule over REAL
measured round-trips: each :class:`CoordinateNode` periodically pings a
random peer, timestamps the pong, and springs its coordinate toward the
observation — so a deployment gets "which replica is closest to me?"
from live traffic, the way Serf/Consul run their network tomography.
The reference offers nothing here (no RTT measurement anywhere; its
keep-alive is the 10-second socket timeout [ref:
p2pnetwork/nodeconnection.py:47]).

Wire protocol (dict payloads over the ordinary frame format, invisible
to application traffic like every other protocol layer in this
package):

- ``{"_viv_ping": seq}`` — answered as ``{"_viv_pong": seq}`` plus the
  RESPONDER's current coordinate/height/error, so one round-trip yields
  both the RTT sample and the remote state Vivaldi needs;
- the pinger timestamps sends in a local table keyed by ``seq`` and
  computes ``rtt`` on the pong from its own monotonic clock (no clock
  sync, no timestamps on the wire), then applies the height-vector
  update (models/vivaldi.py's rule, scalar form). Outstanding entries
  for pongs that never come back are pruned by age on later ticks.

Pings ride :meth:`tick`, called by the application or a timer of its
choosing (the examples use ``loop.call_later`` chains; tests drive it
directly for determinism). Every update runs on the node's event loop;
``coordinate()`` snapshots are safe from any thread.
"""

from __future__ import annotations

import random
import time
from typing import Dict, List, Optional, Tuple

from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

PING_KEY = "_viv_ping"
PONG_KEY = "_viv_pong"


class CoordinateNode(Node):
    """A :class:`Node` maintaining a live Vivaldi coordinate.

    ``dim``/``cc``/``ce_gain``/``height_min`` mirror
    :class:`~p2pnetwork_tpu.models.vivaldi.Vivaldi`; ``rtt_floor``
    clamps measured round-trips (loopback measures microseconds — the
    floor keeps the relative-error arithmetic meaningful)."""

    def __init__(self, *args, dim: int = 2, cc: float = 0.25,
                 ce_gain: float = 0.25, height_min: float = 1e-6,
                 rtt_floor: float = 1e-6, ping_expiry: float = 30.0,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.dim = dim
        self.cc = cc
        self.ce_gain = ce_gain
        self.height_min = height_min
        self.rtt_floor = rtt_floor
        self.ping_expiry = ping_expiry
        rng = random.Random(self.id)
        self._rng = rng
        # Tiny seeded spread — same rationale as the sim model's init.
        self.coord: List[float] = [1e-6 * rng.uniform(-1, 1)
                                   for _ in range(dim)]
        self.height: float = height_min
        self.ce: float = 1.0
        self.samples: int = 0
        self._seq = 0
        self._inflight: Dict[int, float] = {}  # seq -> monotonic send time

    # ------------------------------------------------------------ app API

    def coordinate(self) -> Tuple[List[float], float, float]:
        """Snapshot ``(coord, height, error_estimate)``."""
        return list(self.coord), self.height, self.ce

    def predicted_rtt(self, coord: List[float], height: float) -> float:
        """Predicted RTT to a peer advertising ``(coord, height)``."""
        d = sum((a - b) ** 2 for a, b in zip(self.coord, coord)) ** 0.5
        return d + self.height + height

    def tick(self) -> None:
        """Ping one random peer (no-op with no peers). Thread-safe; call
        from a timer at whatever cadence suits the deployment."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")

        def _do():
            now = time.monotonic()
            # Prune pings whose pong never came back (dead peers): the
            # table would otherwise grow for the node's lifetime.
            if self._inflight:
                stale = [s for s, t in self._inflight.items()
                         if now - t > self.ping_expiry]
                for s in stale:
                    del self._inflight[s]
            peers = self.all_nodes
            if not peers:
                return
            peer = self._rng.choice(peers)
            self._seq += 1
            self._inflight[self._seq] = now
            self.send_to_node(peer, {PING_KEY: self._seq})

        loop.call_soon_threadsafe(_do)

    def coordinate_updated(self, rtt: float) -> None:
        """A sample was absorbed (override / observe; default logs)."""
        self.debug_print(f"coordinate_updated: rtt={rtt:.6f} ce={self.ce:.3f}")

    # ------------------------------------------------------ spring update

    def _absorb(self, rtt: float, r_coord: List[float], r_height: float,
                r_ce: float) -> None:
        if len(r_coord) != self.dim:
            # A peer running a different dimensionality (or a malformed
            # pong) — zip would silently TRUNCATE our coordinate to the
            # shorter length, permanently. Drop the sample instead.
            self.debug_print(
                f"coordinate sample dropped: peer dim {len(r_coord)} != "
                f"ours {self.dim}")
            return
        rtt = max(rtt, self.rtt_floor)
        dvec = [a - b for a, b in zip(self.coord, r_coord)]
        dist = max(sum(d * d for d in dvec) ** 0.5, 1e-12)
        pred = dist + self.height + r_height
        err = pred - rtt
        w = self.ce / max(self.ce + r_ce, 1e-12)
        rel_err = abs(err) / rtt
        delta = self.cc * w
        self.coord = [x - delta * err * (d / dist)
                      for x, d in zip(self.coord, dvec)]
        self.height = max(self.height - delta * err * (self.height / pred),
                          self.height_min)
        self.ce = min(max(rel_err * (self.ce_gain * w)
                          + self.ce * (1.0 - self.ce_gain * w), 0.0), 1.0)
        self.samples += 1
        self.coordinate_updated(rtt)

    # ------------------------------------------------------ interception

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict) and PING_KEY in data:
            self.send_to_node(node, {
                PONG_KEY: data[PING_KEY],
                "coord": list(self.coord), "height": self.height,
                "ce": self.ce,
            })
            return
        if isinstance(data, dict) and PONG_KEY in data:
            sent = self._inflight.pop(data[PONG_KEY], None)
            if sent is not None:
                self._absorb(time.monotonic() - sent,
                             list(data.get("coord") or [0.0] * self.dim),
                             float(data.get("height") or 0.0),
                             float(data.get("ce") or 1.0))
            return
        super().node_message(node, data)
