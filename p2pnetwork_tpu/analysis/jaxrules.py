"""graftlint JAX rules: retrace and host-sync hazards, from the AST alone.

On dense hardware, sim-backend performance is a compilation-discipline
property (PAPER.md; arXiv:1906.11786 makes the same point for sparse GNNs
on TPUs): one stray ``.item()`` in a driver loop serializes every round on
a device->host round trip, and one ``jax.jit`` constructed per call turns
the measured steady state into a permanent warmup. These rules encode the
discipline the BENCH harness otherwise rediscovers as regressions:

========================  =====  ==============================================
rule                      sev    fires on
========================  =====  ==============================================
``jit-in-loop``           P0     ``jax.jit(...)`` constructed inside a
                                 ``for``/``while`` body — a fresh cache per
                                 iteration, retrace every time
``jit-immediate-call``    P1     ``jax.jit(f)(args)`` in one expression — the
                                 compiled program is thrown away after the call
``host-sync-in-loop``     P1     ``.item()``, ``jax.device_get``, ``float()``/
                                 ``int()`` on non-literals, ``np.asarray``/
                                 ``np.array`` (device->host) and
                                 ``jnp.asarray``/``jnp.array`` (host->device)
                                 on non-literals inside explicit loops of a
                                 jax-importing module
``tracer-branch``         P1     Python ``if``/``while`` on a value derived
                                 from a jitted function's traced parameters
                                 (shape/dtype/ndim/len derivations are static
                                 and exempt)
``jit-static-array``      P1     a ``static_argnames``/``static_argnums``
                                 parameter whose default or annotation is an
                                 array — unhashable at best, retrace-per-value
                                 at worst
``jit-closure-ndarray``   P2     a function built inside another function,
                                 closing over a locally-built ``np``/``jnp``
                                 array, then jitted — fresh compile-time
                                 constant (and cache entry) per outer call
``f64-literal``           P2     ``float64`` dtype literals in jax modules —
                                 silently f32 under default x64-off, silently
                                 doubled bandwidth under x64-on
``carry-no-donate``       P2     a jitted function carrying a ``lax`` loop
                                 whose jit wrapper donates nothing — the carry
                                 is double-buffered for the whole run
``unbounded-cache``       P2     a module/class-level dict cache written
                                 inside a function with no eviction anywhere
                                 in the module — every distinct key resident
                                 forever (host memory, and for compiled-
                                 artifact caches, a compile per key)
========================  =====  ==============================================

Detection is deliberately syntactic (stdlib ``ast``; no jax import, no type
inference): conservative enough to run in a sockets-only environment, with
``# graftlint: ignore[...]`` + the baseline absorbing the judged-acceptable
remainder (e.g. the engine's deliberate ``donate=False`` escape-hatch loop
variants).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from p2pnetwork_tpu.analysis.core import Module, register_rule

#: Attribute accesses on a tracer that yield static (trace-time) values —
#: branching on these is shape polymorphism, not a tracer leak.
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding",
                           "weak_type"})
#: Calls whose result is static regardless of traced arguments.
_STATIC_CALLS = frozenset({"len", "isinstance", "type", "hasattr", "getattr",
                           "callable", "id", "repr"})
_NP_CONSTRUCTORS = frozenset({"array", "asarray", "zeros", "ones", "arange",
                              "full", "eye", "linspace", "empty",
                              "zeros_like", "ones_like", "full_like"})
_ARRAYISH_ANNOTATIONS = frozenset({"ndarray", "Array", "ArrayLike",
                                   "DeviceArray"})


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_dotted(module: Module, node: ast.AST) -> Optional[str]:
    """Canonical dotted path of a Name/Attribute, import aliases expanded:
    with ``import jax.numpy as jnp``, ``jnp.float64`` -> ``jax.numpy.
    float64``; with ``from jax import jit``, ``jit`` -> ``jax.jit``."""
    dotted = dotted_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    if head in module.from_imports:
        head = module.from_imports[head]
    elif head in module.aliases:
        # ``import numpy as np`` -> np resolves to numpy. A bare
        # ``import jax.numpy`` binds "jax", which aliases map correctly.
        target = module.aliases[head]
        if head != target:
            head = target
    return f"{head}.{rest}" if rest else head


def _is_jit_ref(module: Module, node: ast.AST) -> bool:
    return resolve_dotted(module, node) == "jax.jit"


def jit_call_info(module: Module, call: ast.Call
                  ) -> Optional[Tuple[Optional[ast.AST], List[ast.keyword]]]:
    """If ``call`` constructs a jitted program, return ``(wrapped, jit
    kwargs)`` — handles ``jax.jit(f, **kw)`` and ``functools.partial(
    jax.jit, **kw)`` (wrapped=None for the partial form, whose target
    arrives at the later call site)."""
    if _is_jit_ref(module, call.func):
        wrapped = call.args[0] if call.args else None
        return wrapped, list(call.keywords)
    if (resolve_dotted(module, call.func) == "functools.partial"
            and call.args and _is_jit_ref(module, call.args[0])):
        return None, list(call.keywords)
    return None


def jitted_function_params(module: Module, fn: ast.FunctionDef
                           ) -> Optional[Tuple[Set[str], List[ast.keyword]]]:
    """If ``fn`` is jit-decorated, the set of its TRACED parameter names
    (static args removed) plus the jit kwargs; else None."""
    for deco in fn.decorator_list:
        kwargs: Optional[List[ast.keyword]] = None
        if _is_jit_ref(module, deco):
            kwargs = []
        elif isinstance(deco, ast.Call):
            info = jit_call_info(module, deco)
            if info is not None:
                kwargs = info[1]
        if kwargs is None:
            continue
        return _traced_params(fn, kwargs), kwargs
    return None


def _static_names_nums(kwargs: Sequence[ast.keyword]
                       ) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in kwargs:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


def _traced_params(fn: ast.FunctionDef,
                   kwargs: Sequence[ast.keyword]) -> Set[str]:
    static_names, static_nums = _static_names_nums(kwargs)
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    traced = {p for i, p in enumerate(params)
              if p not in static_names and i not in static_nums}
    traced.update(a.arg for a in fn.args.kwonlyargs
                  if a.arg not in static_names)
    traced.discard("self")
    return traced


def _tracer_value_names(node: ast.AST) -> Set[str]:
    """Names whose *traced value* (not just static metadata) feeds ``node``.
    ``x.shape[0] > 4`` contributes nothing; ``jnp.any(x)`` contributes x."""
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return set()
        return _tracer_value_names(node.value)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _STATIC_CALLS:
            return set()
        out: Set[str] = set()
        if isinstance(fn, ast.Attribute):  # x.sum() taints through x
            out |= _tracer_value_names(fn.value)
        for a in node.args:
            out |= _tracer_value_names(a)
        for kw in node.keywords:
            out |= _tracer_value_names(kw.value)
        return out
    if isinstance(node, ast.Name):
        return {node.id}
    out = set()
    for child in ast.iter_child_nodes(node):
        out |= _tracer_value_names(child)
    return out


# ----------------------------------------------------------------- rules


@register_rule(
    "jit-in-loop", "P0",
    "jax.jit constructed inside a loop body: a fresh wrapper (and compile "
    "cache) per iteration — the program retraces every time around.")
def rule_jit_in_loop(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    seen: set = set()  # a call nested in N loops is still ONE finding
    for loop in ast.walk(module.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for node in ast.walk(loop):
            if (isinstance(node, ast.Call) and id(node) not in seen
                    and jit_call_info(module, node)):
                seen.add(id(node))
                yield node, ("jax.jit constructed inside a loop — hoist the "
                             "jitted function out of the loop so its compile "
                             "cache survives across iterations")


@register_rule(
    "jit-immediate-call", "P1",
    "jax.jit(f)(args) in one expression: the compiled program is built, "
    "called once, and thrown away — every evaluation retraces.")
def rule_jit_immediate_call(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    for node in ast.walk(module.tree):
        # Only the direct ``jax.jit(f)(args)`` shape: the partial form
        # ``partial(jax.jit, ...)(f)`` is jit *construction* — calling it
        # once yields the reusable jitted function, not a result.
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Call)
                and _is_jit_ref(module, node.func.func)):
            yield node, ("jit-compile-and-call in one expression — bind the "
                         "jitted function once (module level or a cached "
                         "factory) and call the binding")


@register_rule(
    "host-sync-in-loop", "P1",
    "Host-synchronizing op inside an explicit loop of a jax module: each "
    "iteration blocks on a device->host transfer, serializing the loop.")
def rule_host_sync_in_loop(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    np_names = module.names_for("numpy")
    jnp_names = module.names_for("jax.numpy")
    seen: Set[int] = set()
    for loop in ast.walk(module.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            msg = None
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                    and not node.args:
                msg = (".item() in a loop — a device->host sync per "
                       "iteration; batch with device_get after the loop or "
                       "keep the reduction on-device")
            elif resolve_dotted(module, fn) == "jax.device_get":
                msg = ("jax.device_get in a loop — transfer once after the "
                       "loop (device_get takes whole pytrees)")
            elif (isinstance(fn, ast.Name) and fn.id in ("float", "int")
                  and len(node.args) == 1
                  and not isinstance(node.args[0], ast.Constant)):
                msg = (f"{fn.id}() on a non-literal in a loop — forces the "
                       "value to host every iteration when it is a jax "
                       "array; keep it on-device or convert after the loop")
            elif (isinstance(fn, ast.Attribute)
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id in (np_names | jnp_names)
                  and fn.attr in ("asarray", "array")
                  and node.args
                  and not isinstance(node.args[0], (ast.Constant, ast.List,
                                                    ast.Tuple))):
                # np.* forces the value to HOST each iteration when fed a
                # jax array; jnp.* forces it to DEVICE each iteration when
                # fed host data — either direction is a per-iteration
                # transfer serializing the loop.
                direction = ("device->host" if fn.value.id in np_names
                             else "host->device")
                msg = (f"{fn.value.id}.{fn.attr}() on a non-literal in a "
                       f"loop — a {direction} transfer per iteration; "
                       "convert once outside the loop")
            if msg is not None:
                seen.add(id(node))
                yield node, msg


@register_rule(
    "tracer-branch", "P1",
    "Python control flow on a traced value inside a jitted function: "
    "raises TracerBoolConversionError at trace time, or — behind a "
    "static_argnums escape — retraces per value.")
def rule_tracer_branch(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    for fn in ast.walk(module.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        info = jitted_function_params(module, fn)
        if info is None:
            continue
        tainted = set(info[0])
        # One forward pass of value-taint through simple assignments; loops
        # in dataflow are rare enough in jitted bodies to not need a
        # fixpoint.
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if _tracer_value_names(node.value) & tainted:
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hot = _tracer_value_names(node.test) & tainted
                if hot:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    yield node, (
                        f"Python `{kind}` on traced value(s) "
                        f"{sorted(hot)} inside jitted `{fn.name}` — use "
                        "lax.cond/lax.select (or jnp.where), or mark the "
                        "argument static if it is genuinely configuration")


@register_rule(
    "jit-static-array", "P1",
    "A static_argnames/static_argnums parameter that is array-valued: "
    "unhashable (TypeError) or, via tuple conversion, a retrace per value.")
def rule_jit_static_array(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    np_like = module.names_for("numpy") | module.names_for("jax.numpy")
    for fn in ast.walk(module.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        info = jitted_function_params(module, fn)
        if info is None:
            continue
        static_names, static_nums = _static_names_nums(info[1])
        args = fn.args.posonlyargs + fn.args.args
        statics = [a for i, a in enumerate(args)
                   if a.arg in static_names or i in static_nums]
        statics += [a for a in fn.args.kwonlyargs if a.arg in static_names]
        defaults = _param_defaults(fn)
        for a in statics:
            why = None
            ann = a.annotation
            if ann is not None:
                names = {n.attr if isinstance(n, ast.Attribute) else
                         getattr(n, "id", None) for n in ast.walk(ann)}
                if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                    names |= set(ann.value.replace(".", " ").split())
                if names & _ARRAYISH_ANNOTATIONS:
                    why = "annotated as an array"
            default = defaults.get(a.arg)
            if why is None and default is not None:
                if isinstance(default, (ast.List, ast.Set)):
                    why = "defaulted to an unhashable literal"
                elif isinstance(default, ast.Call):
                    fn_path = resolve_dotted(module, default.func) or ""
                    head = fn_path.rsplit(".", 1)
                    if (isinstance(default.func, ast.Attribute)
                            and isinstance(default.func.value, ast.Name)
                            and default.func.value.id in np_like
                            and default.func.attr in _NP_CONSTRUCTORS) or \
                            (len(head) == 2 and head[0] in ("numpy",
                                                            "jax.numpy")
                             and head[1] in _NP_CONSTRUCTORS):
                        why = "defaulted to a constructed array"
            if why is not None:
                yield a, (f"static jit argument `{a.arg}` of `{fn.name}` is "
                          f"{why} — arrays are not hashable static values; "
                          "pass it traced, or reduce it to a hashable "
                          "summary (shape/tuple) before the jit boundary")


def _param_defaults(fn: ast.FunctionDef) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    pos = fn.args.posonlyargs + fn.args.args
    for a, d in zip(pos[len(pos) - len(fn.args.defaults):], fn.args.defaults):
        out[a.arg] = d
    for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if d is not None:
            out[a.arg] = d
    return out


@register_rule(
    "jit-closure-ndarray", "P2",
    "A jitted inner function closes over an ndarray built in the enclosing "
    "function: every outer call bakes a fresh compile-time constant and "
    "misses the compile cache.")
def rule_jit_closure_ndarray(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    np_like = module.names_for("numpy") | module.names_for("jax.numpy")

    def is_array_build(value: ast.AST) -> bool:
        return (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and isinstance(value.func.value, ast.Name)
                and value.func.value.id in np_like
                and value.func.attr in _NP_CONSTRUCTORS)

    for outer in ast.walk(module.tree):
        if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        array_locals: Set[str] = set()
        inner_defs: Dict[str, ast.FunctionDef] = {}
        for stmt in ast.walk(outer):
            if isinstance(stmt, ast.Assign) and is_array_build(stmt.value):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        array_locals.add(tgt.id)
            if isinstance(stmt, ast.FunctionDef) and stmt is not outer:
                inner_defs[stmt.name] = stmt
        if not array_locals or not inner_defs:
            continue

        def captures(inner: ast.FunctionDef) -> Set[str]:
            bound = {a.arg for a in inner.args.posonlyargs + inner.args.args
                     + inner.args.kwonlyargs}
            return {n.id for n in ast.walk(inner)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in array_locals and n.id not in bound}

        for node in ast.walk(outer):
            inner = None
            site = node
            if isinstance(node, ast.Call):
                info = jit_call_info(module, node)
                if info and isinstance(info[0], ast.Name):
                    inner = inner_defs.get(info[0].id)
            elif isinstance(node, ast.FunctionDef) and node.name in inner_defs:
                if jitted_function_params(module, node) is not None:
                    inner = node
            if inner is None:
                continue
            caught = captures(inner)
            if caught:
                yield site, (
                    f"jitted `{inner.name}` closes over locally-built "
                    f"array(s) {sorted(caught)} — each call of "
                    f"`{outer.name}` bakes them in as fresh constants and "
                    "retraces; pass them as traced arguments instead")


@register_rule(
    "f64-literal", "P2",
    "float64 dtype literal in a jax module: silently downcast to f32 under "
    "the default x64-off config, silently doubles bandwidth under x64-on — "
    "either way it drifts from the sim's f32 discipline.")
def rule_f64_literal(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return
    for node in ast.walk(module.tree):
        resolved = resolve_dotted(module, node) if isinstance(
            node, (ast.Attribute, ast.Name)) else None
        if resolved in ("numpy.float64", "jax.numpy.float64"):
            yield node, ("float64 dtype literal — pick an explicit f32 (or "
                         "express the precision need in one place) instead "
                         "of depending on the x64 flag")
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if (kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in ("float64", "f64", "double")):
                    yield kw.value, (
                        "dtype=\"float64\" literal — same x64-flag drift as "
                        "jnp.float64; use an explicit f32 dtype")


@register_rule(
    "carry-no-donate", "P2",
    "A jitted function carrying a lax while_loop/scan/fori_loop donates "
    "nothing: the carry state is double-buffered (input + output) for the "
    "whole run — at 1M-node state sizes that is real HBM.")
def rule_carry_no_donate(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    if not module.imports_package("jax"):
        return

    def has_lax_loop(fn: ast.FunctionDef) -> bool:
        """True when a lax loop in ``fn`` is seeded with a *parameter* —
        only then can donating the jit argument recycle the carry. A
        carry constructed inside the function (e.g. a fresh zeros field)
        is XLA's to buffer; donation has nothing to offer it."""
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            path = resolve_dotted(module, node.func) or ""
            init: Optional[ast.AST] = None
            if path == "jax.lax.while_loop" and len(node.args) >= 3:
                init = node.args[2]
            elif path == "jax.lax.scan":
                init = (node.args[1] if len(node.args) >= 2 else
                        next((kw.value for kw in node.keywords
                              if kw.arg == "init"), None))
            elif path == "jax.lax.fori_loop" and len(node.args) >= 4:
                init = node.args[3]
            if init is None:
                continue
            names = {n.id for n in ast.walk(init)
                     if isinstance(n, ast.Name)}
            if names & params:
                return True
        return False

    def donates(kwargs: Sequence[ast.keyword]) -> bool:
        return any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in kwargs)

    local_fns = {fn.name: fn for fn in ast.walk(module.tree)
                 if isinstance(fn, ast.FunctionDef)}

    # Decorator form: @jax.jit / @partial(jax.jit, ...) on a loop-carrying fn.
    for fn in local_fns.values():
        info = jitted_function_params(module, fn)
        if info is not None and not donates(info[1]) and has_lax_loop(fn):
            yield fn, (f"jitted `{fn.name}` carries a lax loop but donates "
                       "no arguments — pass donate_argnums/donate_argnames "
                       "for the carry (or suppress where double-buffering "
                       "is the documented contract)")

    # Call form: jax.jit(fn, ...) / partial(jax.jit, ...)(fn) on a named fn.
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        wrapped: Optional[ast.AST] = None
        kwargs: List[ast.keyword] = []
        info = jit_call_info(module, node)
        if info is not None:
            wrapped, kwargs = info
        elif isinstance(node.func, ast.Call):
            inner = jit_call_info(module, node.func)
            # Only the partial(jax.jit, **kw)(fn) shape — inner wrapped
            # is None because the target arrives here. Direct
            # jax.jit(f)(x) also has a jit inner call, but node.args[0]
            # is then the RUNTIME argument x, not a function being
            # wrapped (and that shape is jit-immediate-call's to flag).
            if inner is not None and inner[0] is None:
                wrapped = node.args[0] if node.args else None
                kwargs = list(node.func.keywords)
        if not isinstance(wrapped, ast.Name) or donates(kwargs):
            continue
        target = local_fns.get(wrapped.id)
        if target is not None and jitted_function_params(module, target) \
                is None and has_lax_loop(target):
            yield node, (f"jit of loop-carrying `{wrapped.id}` donates no "
                         "arguments — pass donate_argnums/donate_argnames "
                         "for the carry (or suppress where double-buffering "
                         "is the documented contract)")


@register_rule(
    "unbounded-cache", "P2",
    "A module/class-level dict cache written inside a function with no "
    "eviction anywhere in the module: every distinct key stays resident "
    "for the process lifetime — memoization that looks free until the "
    "key space turns out to be user-shaped.")
def rule_unbounded_cache(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    # The _rec_ici_round_bytes pattern: `_CACHE: dict = {}` at module (or
    # class) scope, `_CACHE[key] = build(...)` inside a function, nothing
    # anywhere that ever removes an entry. Deliberately bounded caches
    # (finite key vocabulary) suppress with the rationale on the
    # DECLARATION line — that is where the finding anchors.

    def _empty_dict(value: Optional[ast.AST]) -> bool:
        if isinstance(value, ast.Dict) and not value.keys:
            return True
        return (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "dict"
                and not value.args and not value.keywords)

    def _decl_of(body: Sequence[ast.stmt]) -> Iterable[Tuple[str, ast.AST]]:
        for stmt in body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and _empty_dict(stmt.value)):
                yield stmt.targets[0].id, stmt
            elif (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and _empty_dict(stmt.value)):
                yield stmt.target.id, stmt

    caches: Dict[str, ast.AST] = dict(_decl_of(module.tree.body))
    for cls in ast.walk(module.tree):
        if isinstance(cls, ast.ClassDef):
            # A class-body dict is ONE shared mapping per class —
            # self._cache[k] = v from any instance grows it globally.
            caches.update(_decl_of(cls.body))
    if not caches:
        return

    def _base(expr: ast.AST) -> Optional[str]:
        """The cache a subscript/method target names: bare ``NAME`` or
        the shared class dict through ``self``/``cls``."""
        if isinstance(expr, ast.Name):
            return expr.id
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id in ("self", "cls")):
            return expr.attr
        return None

    evicted: Set[str] = set()
    writes: Dict[str, Tuple[str, int]] = {}  # cache -> (fn, write count)
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Delete):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        name = _base(tgt.value)
                        if name in caches:
                            evicted.add(name)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                name = _base(node.func.value)
                if name in caches:
                    if node.func.attr in ("pop", "popitem", "clear"):
                        evicted.add(name)
                    elif node.func.attr == "setdefault" \
                            and len(node.args) >= 2:
                        had = writes.get(name, (fn.name, 0))
                        writes[name] = (had[0], had[1] + 1)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript):
                        name = _base(tgt.value)
                        if name in caches:
                            had = writes.get(name, (fn.name, 0))
                            writes[name] = (had[0], had[1] + 1)
                    elif isinstance(tgt, ast.Name) and tgt.id in caches:
                        # A function-scope rebind (`CACHE = {}`) resets
                        # the mapping — eviction by replacement.
                        evicted.add(tgt.id)

    for name, (fn_name, count) in sorted(writes.items()):
        if name in evicted:
            continue
        more = f" (and {count - 1} more site(s))" if count > 1 else ""
        yield caches[name], (
            f"dict cache `{name}` grows inside `{fn_name}`{more} with no "
            "eviction anywhere in the module — bound it (maxsize + "
            "pop/clear, or functools.lru_cache) or suppress here with "
            "the rationale for why its key space is finite")
