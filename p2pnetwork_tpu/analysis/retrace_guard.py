"""Runtime complement to graftlint: per-block jit compile budgets.

The static rules (jaxrules.py) catch retrace hazards the AST can prove;
everything else — shape churn from data, a cache key that includes an
unhashed ndarray id, a library upgrade that changed tracing — only shows
up as the compile counter climbing at runtime. :class:`retrace_guard`
turns that counter into an assertion: wrap a block, declare how many
backend compiles it is *allowed* to cost, and breaches become exceptions
(tests), structured warnings (benches), or a callback (drivers).

Counting rides the PR-1 telemetry jaxhooks (``jax.monitoring`` duration
events -> ``jax_compiles_total``), so a guard sees every XLA backend
compile in the process, wherever it was triggered from. Guards therefore
measure *process-wide* compiles during the block: run them around
single-flow regions (a bench stage, one test body), not concurrently.

Usage::

    from p2pnetwork_tpu.analysis import retrace_guard

    with retrace_guard("steady-state", budget=0):
        for _ in range(100):
            step(state)          # raises RetraceBudgetExceeded if any
                                 # iteration recompiles

    with retrace_guard("bench-1m", budget=24, on_breach="warn") as g:
        run_stage()
    print(g.compiles, g.breached)

Without jax (sockets-only environment) the guard is an inert no-op that
reports zero compiles — importable anywhere the linter is.
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional, Union

from p2pnetwork_tpu.telemetry.registry import Registry, default_registry

__all__ = ["retrace_guard", "RetraceBudgetExceeded"]


class RetraceBudgetExceeded(RuntimeError):
    """A guarded block compiled more jit programs than its budget."""

    def __init__(self, block: str, compiles: int, budget: int):
        self.block = block
        self.compiles = compiles
        self.budget = budget
        super().__init__(
            f"retrace_guard[{block}]: {compiles} backend compile(s), "
            f"budget {budget} — something inside retraces per call "
            f"(shape churn, fresh jit wrappers, or unhashable statics)")


class retrace_guard:
    """Context manager asserting a compile budget over its block.

    Parameters
    ----------
    block:
        Label for errors, warnings and the telemetry counters
        (``retrace_guard_compiles_total{block}`` /
        ``retrace_guard_breaches_total{block}``).
    budget:
        Maximum backend compiles the block may trigger. 0 is the
        steady-state contract: everything warm, nothing retraces.
    registry:
        Telemetry registry to count into (default: the process default).
    on_breach:
        ``"raise"`` (default) — raise :class:`RetraceBudgetExceeded`;
        ``"warn"`` — emit a ``RuntimeWarning`` and keep going; or a
        callable receiving the guard (benches route this into their
        structured-warning stream). Exceptions already propagating out
        of the block take precedence — the guard never masks them.
    """

    def __init__(self, block: str, budget: int,
                 registry: Optional[Registry] = None,
                 on_breach: Union[str, Callable[["retrace_guard"],
                                                None]] = "raise"):
        if budget < 0:
            raise ValueError("budget must be >= 0")
        if not (on_breach in ("raise", "warn") or callable(on_breach)):
            raise ValueError("on_breach must be 'raise', 'warn' or callable")
        self.block = str(block)
        self.budget = int(budget)
        self.on_breach = on_breach
        self._registry = registry
        self._start: Optional[float] = None
        #: Backend compiles observed during the block (valid after exit).
        self.compiles: int = 0
        #: Whether the block exceeded its budget (valid after exit).
        self.breached: bool = False
        self._active = False

    # ------------------------------------------------------------ helpers

    def _reg(self) -> Registry:
        return self._registry if self._registry is not None \
            else default_registry()

    def _count(self) -> Optional[float]:
        """Current process-wide compile count, or None when jax (or its
        monitoring hooks) is unavailable — the guard then no-ops."""
        from p2pnetwork_tpu.telemetry import jaxhooks

        if not jaxhooks.install(self._registry):
            return None
        return jaxhooks.compile_count(self._registry)

    # ------------------------------------------------------------ protocol

    def __enter__(self) -> "retrace_guard":
        if self._active:
            raise RuntimeError("retrace_guard is not reentrant")
        self._active = True
        self.compiles = 0
        self.breached = False
        self._start = self._count()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._active = False
        if self._start is None:
            return False  # no jax — nothing measured, nothing enforced
        end = self._count()
        if end is None:
            return False
        self.compiles = int(end - self._start)
        reg = self._reg()
        reg.counter(
            "retrace_guard_compiles_total",
            "Backend compiles observed inside retrace_guard blocks.",
            ("block",)).labels(self.block).inc(self.compiles)
        self.breached = self.compiles > self.budget
        if not self.breached:
            return False
        reg.counter(
            "retrace_guard_breaches_total",
            "retrace_guard blocks that exceeded their compile budget.",
            ("block",)).labels(self.block).inc()
        if exc_type is not None:
            return False  # the block's own failure outranks the breach
        if self.on_breach == "raise":
            raise RetraceBudgetExceeded(self.block, self.compiles,
                                        self.budget)
        if self.on_breach == "warn":
            warnings.warn(
                f"retrace_guard[{self.block}]: {self.compiles} compile(s) "
                f"over budget {self.budget}", RuntimeWarning, stacklevel=2)
        else:
            self.on_breach(self)
        return False
