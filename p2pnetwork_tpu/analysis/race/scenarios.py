"""graftrace scenario battery: the threaded plane's hazard surfaces as
deterministic, seed-explorable bodies.

Each scenario drives REAL library objects — nodes, the chaos plane, the
watchdog/checkpoint pair, the telemetry registry — from managed threads
that mirror the production thread roles (one "loop" thread for
loop-confined state, plus the foreign threads the public API documents
as safe callers). No sockets traffic flows and no event loop runs: what
is under test is exactly the cross-thread shared-state discipline, which
is the part the asyncio confinement does NOT cover and chaos soaks only
sample. Lock-guarded attributes are auto-tracked
(:func:`~p2pnetwork_tpu.analysis.race.detector.watch`), so any
unordered conflicting access — or any deadlock — in ANY explored
schedule fails the gate.

Determinism rules for scenario authors:

- pass explicit ``now=`` timestamps into everything that branches on
  time (phi sweeps, quarantine evictions) — wall clock must never pick
  the code path;
- iterate deterministically (dicts, sorted sets);
- close what you open (sockets, watchdog threads) inside the body, so a
  schedule ends with every task finished.

Scenarios self-describe optional dependencies: a factory raising
:class:`ScenarioUnavailable` (e.g. no jax for the supervise scenario)
reports as a skip with its reason, never as a crash of the battery.
"""

from __future__ import annotations

import shutil
import tempfile
from typing import Callable, Dict, List, NamedTuple

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.analysis.race.detector import watch

__all__ = ["SCENARIOS", "ScenarioUnavailable", "scenario", "builtin_names"]


class ScenarioUnavailable(RuntimeError):
    """Raised by a factory whose dependencies are absent on this image;
    the battery reports a skip with this reason."""


class _Scenario(NamedTuple):
    name: str
    doc: str
    factory: Callable[[], Callable[[], None]]
    builtin: bool


#: name -> scenario. Builtins are the CI battery; externally registered
#: scenarios (``--scenarios-from``, test fixtures) join the registry but
#: not the default gate.
SCENARIOS: Dict[str, _Scenario] = {}  # graftlint: ignore[unbounded-cache] -- scenario registry: builtins at import plus explicit --scenarios-from registrations, not per-request growth


def scenario(name: str, doc: str, *, builtin: bool = True):
    """Register a scenario factory. The factory runs OUTSIDE the managed
    world (imports, dependency checks); the body it returns runs as the
    managed main task, once per explored schedule."""
    def deco(factory):
        # Last registration wins: an external scenarios file is loaded
        # both by import and by --scenarios-from in the same process
        # (tests do), and re-registration must refresh, not crash.
        SCENARIOS[name] = _Scenario(name, doc, factory, builtin)
        return factory
    return deco


def builtin_names() -> List[str]:
    return [n for n, s in sorted(SCENARIOS.items()) if s.builtin]


# --------------------------------------------------------------- helpers

class _StubConn:
    """The NodeConnection surface the registry/chaos/phi paths touch:
    id/host/port, a thread-safe stop(), a counting send(). No transport."""

    def __init__(self, id: str, host: str = "127.0.0.1", port: int = 0):
        self.id = str(id)
        self.host = host
        self.port = port
        self.stopped = concurrency.event()
        self.sent: int = 0

    def stop(self) -> None:
        self.stopped.set()

    def send(self, data, compression: str = "none") -> None:
        self.sent += 1


def _fresh_registry():
    # Constructed inside the managed body so its locks are instrumented.
    from p2pnetwork_tpu import telemetry
    return telemetry.Registry()


# -------------------------------------------------------------- scenarios

@scenario(
    "connect_disconnect_storm",
    "Peer registry churn under chaos severing: a loop-role thread "
    "registers/deregisters connections via node_disconnected while "
    "foreign threads broadcast, trigger reconnect checks and the chaos "
    "plane kills/partitions/revives — the recovery surface PR 2 soaks, "
    "here under every explored interleaving.")
def _connect_disconnect_storm():
    from p2pnetwork_tpu.chaos.plane import ChaosPlane
    from p2pnetwork_tpu.node import Node

    def body():
        reg = _fresh_registry()
        node = Node("127.0.0.1", 0, id="n0", registry=reg)
        try:
            plane = watch(ChaosPlane(seed=7, registry=reg))
            watch(node.event_log)
            plane.attach(node)
            conns = [_StubConn(f"p{i}") for i in range(4)]
            node.nodes_inbound.extend(conns[:2])
            node.nodes_outbound.extend(conns[2:])

            def loop_role():
                # The event-loop thread's share: registry mutation plus
                # upward dispatch (event log, conn gauges).
                node.node_disconnected(conns[0])
                node.nodes_inbound.append(conns[0])
                node.node_disconnected(conns[2])
                node.nodes_outbound.append(conns[2])

            def broadcaster():
                for _ in range(3):
                    node.send_to_nodes({"k": 1})
                    # Apps log custom events from their own threads; the
                    # EventLog is documented thread-safe, so the storm
                    # must drive it cross-thread (the loop role records
                    # disconnect events into the same deque).
                    node.event_log.record("app_note", None, {})

            def chaos_role():
                plane.kill_nodes(["p0"])
                plane.partition([["n0", "p1"], ["p2", "p3"]])
                plane.heal_partition()
                plane.revive_nodes(["p0"])
                plane.cut_links([("n0", "p3")])
                plane.heal_links([("n0", "p3")])

            def prober():
                for a, b in (("n0", "p0"), ("n0", "p1"), ("n0", "p3")):
                    plane.link_ok(a, b)
                plane.fault_log()
                node.event_log.count("inbound_node_disconnected")
                node.event_log.snapshot()

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("loop", loop_role), ("bcast", broadcaster),
                                ("chaos", chaos_role), ("probe", prober))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
            plane.detach(node)
        finally:
            node.sock.close()
    return body


@scenario(
    "phi_quarantine",
    "Phi quarantine transitions under concurrent sweeps: heartbeats land "
    "while a loop-role tick and a monitoring thread both evaluate "
    "quarantine/readmit/evict, a peer disconnects mid-sweep, and the "
    "chaos plane severs — the _phi_lock discipline PR 4 restructured, "
    "checked dynamically.")
def _phi_quarantine():
    from p2pnetwork_tpu.chaos.plane import ChaosPlane
    from p2pnetwork_tpu.phi import PhiAccrualNode

    def body():
        reg = _fresh_registry()
        node = PhiAccrualNode(
            "127.0.0.1", 0, id="n0", window=8, quarantine_threshold=2.0,
            evict_after=50.0, registry=reg)
        try:
            watch(node)
            plane = watch(ChaosPlane(seed=3, registry=reg))
            plane.attach(node)
            conns = [_StubConn(f"p{i}") for i in range(3)]
            node.nodes_inbound.extend(conns)

            def heartbeats():
                # A healthy cadence for p0, then silence; p1 heartbeats
                # throughout. Explicit timestamps: the detector must see
                # the same arithmetic in every schedule.
                for t in range(1, 9):
                    node._record_heartbeat("p0", now=float(t))  # graftlint: ignore[host-sync-in-loop] -- plain int loop index, not a device value
                for t in range(1, 17):
                    node._record_heartbeat("p1", now=float(t))  # graftlint: ignore[host-sync-in-loop] -- plain int loop index, not a device value

            def tick_sweep():
                # The loop-role tick: quarantines p0 once its silence
                # stretches (phi at now=200 is astronomically high).
                node.check_quarantine(now=200.0)
                node.check_quarantine(now=300.0)  # evict_after exceeded

            def monitor_sweep():
                node.phi("p0", now=250.0)
                node.check_quarantine(now=250.0)
                node.is_quarantined("p0")
                node.suspicion_levels()

            def churn():
                node.node_disconnected(conns[2])
                plane.kill_nodes(["p1"])
                plane.revive_nodes(["p1"])

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("hb", heartbeats), ("tick", tick_sweep),
                                ("mon", monitor_sweep), ("churn", churn))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
            plane.detach(node)
        finally:
            node.sock.close()
    return body


@scenario(
    "crdt_merge_storm",
    "CRDT merge storm: inbound state merges on the loop-role thread race "
    "create-on-miss accessors from foreign threads — the lost-update "
    "window _crdt_lock exists for, and the dynamic verdict on the "
    "merge-under-lock hazard graftlint grandfathered in PR 4.")
def _crdt_merge_storm():
    from p2pnetwork_tpu.crdt import CRDTNode

    def body():
        reg = _fresh_registry()
        node = CRDTNode("127.0.0.1", 0, id="n0", registry=reg)
        try:
            watch(node)
            src = _StubConn("peer")

            def merges():
                # The loop-role thread: one merge stream, first-contact
                # construct-and-retry included (the baseline entry's
                # exact line runs here, under every explored schedule).
                for i in range(1, 4):
                    node.node_message(src, {
                        "_crdt": "hits", "kind": "gcounter",
                        "state": {"counts": {"peer": i}}})
                node.node_message(src, {
                    "_crdt": "names", "kind": "orset",
                    "state": {"adds": {"a": [["peer", 1]]},
                              "tombs": [], "next": 1}})

            def accessor_a():
                node.gcounter("hits").value
                node.gcounter("fresh").value  # create-on-miss race

            def accessor_b():
                node.set_("names").elements()
                node.gcounter("hits").value

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("loop", merges), ("acc-a", accessor_a),
                                ("acc-b", accessor_b))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
        finally:
            node.sock.close()
    return body


@scenario(
    "registry_storm",
    "Concurrent metric creation: racing get-or-create of families and "
    "labeled children, updates, and snapshot/value readers — the "
    "setdefault re-check discipline telemetry/registry.py documents, "
    "checked under every explored interleaving.")
def _registry_storm():
    def body():
        from p2pnetwork_tpu.telemetry.registry import Registry
        reg = watch(Registry())

        def creator_a():
            c = watch(reg.counter("storm_total", "x", ("who",)))
            c.labels("a").inc()
            reg.gauge("storm_gauge", "y").set(1.0)

        def creator_b():
            c = watch(reg.counter("storm_total", "x", ("who",)))
            c.labels("a").inc()
            c.labels("b").inc(2.0)
            reg.histogram("storm_hist", "z").observe(0.5)

        def reader():
            reg.value("storm_total", who="a")
            reg.snapshot()
            reg.collect()

        ts = [concurrency.thread(target=f, name=nm)
              for nm, f in (("mk-a", creator_a), ("mk-b", creator_b),
                            ("read", reader))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
    return body


@scenario(
    "watchdog_emergency_checkpoint",
    "Watchdog stall firing emergency_checkpoint from the on-stall "
    "thread while the run thread swaps the fallback and saves boundary "
    "checkpoints — the _fb_lock/_save_lock discipline PR 5 documents as "
    "thread-safe, driven from the exact threads it promises.")
def _watchdog_emergency_checkpoint():
    try:
        import jax
        import numpy as np  # noqa: F401
        from p2pnetwork_tpu.supervise.runner import SupervisedRun
        from p2pnetwork_tpu.supervise.store import CheckpointStore
        from p2pnetwork_tpu.supervise.watchdog import Watchdog
    except Exception as e:  # pragma: no cover - jax-less image
        raise ScenarioUnavailable(f"needs jax/supervise: {e}") from e
    import numpy as np
    key = jax.random.key(0)
    state = {"x": np.arange(4, dtype=np.int32)}

    def body():
        reg = _fresh_registry()
        tmp = tempfile.mkdtemp(prefix="graftrace_wd_")
        try:
            store = watch(CheckpointStore(tmp, retain=2, registry=reg))
            run = watch(SupervisedRun(
                None, None, store, chunk_rounds=4, registry=reg))
            hook_saved = []

            def on_stall(dog):
                # The documented on-stall driver seam, from the
                # watchdog-role thread: persist the live fallback.
                hook_saved.append(run.emergency_checkpoint())

            wd = watch(Watchdog(deadline_s=60.0, name="graftrace",
                                on_stall=on_stall, registry=reg))
            wd.start()

            def run_role():
                # Chunk boundaries: publish fallback, save, retract.
                for rnd in (4, 8):
                    run._set_fallback((state, key, rnd, 0))
                    store.save(state, key, rnd, 0)
                    run._set_fallback(None)
                    wd.heartbeat()

            def watchdog_role():
                # The detection-time path _watch runs on its own thread:
                # fire a stall while the run thread is mid-boundary.
                wd._fire(75.0)
                wd._fire(80.0)

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("run", run_role),
                                ("stall", watchdog_role))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
            wd.close()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return body


@scenario(
    "serve_admit_storm",
    "The serving front-end's control plane under exploration: foreign "
    "threads submit/poll/cancel/stream while the driver-role thread "
    "runs admission ticks (retire → admit → engine chunk → harvest) — "
    "the submit/poll/driver interleavings SimService._cond exists for, "
    "driven from the exact thread roles the serve API documents.")
def _serve_admit_storm():
    try:
        import jax  # noqa: F401
        from p2pnetwork_tpu.serve.service import (  # noqa: F401
            Rejected, SimService)
        from p2pnetwork_tpu.sim import graph as G
    except Exception as e:  # pragma: no cover - jax-less image
        raise ScenarioUnavailable(f"needs jax/serve: {e}") from e
    # Built OUTSIDE the managed world: the graph is immutable input, and
    # its construction (native sorts, jit warmup) is not under test.
    g = G.watts_strogatz(24, 4, 0.1, seed=1, source_csr=True)
    # Warm the engine path outside the managed world too: the first
    # batched run lazily registers the default-registry sim_* families
    # (and compiles the batch loop). Registered under an installed
    # provider, those PROCESS-GLOBAL metric locks would be bound to one
    # schedule's scheduler and explode in the next ("graftrace
    # primitives are confined to managed tasks"); warmed here they are
    # raw stdlib locks, and every explored schedule starts compile-hot.
    warm = SimService(g, capacity=8, queue_depth=3, chunk_rounds=4, seed=0)
    warm.submit(1)
    warm.tick()
    warm.close()

    def body():
        from p2pnetwork_tpu.serve.service import Rejected, SimService
        reg = _fresh_registry()
        svc = watch(SimService(
            g, capacity=8, queue_depth=3, chunk_rounds=4, seed=0,
            quotas={"metered": (1.0, 2.0)}, registry=reg))

        def driver_role():
            # The admission-control loop's share, run synchronously so
            # a wedged schedule is a graftrace deadlock, not a hang.
            for _ in range(3):
                svc.tick()

        def submitter_a():
            for s in (1, 2, 3):
                try:
                    svc.submit(s)
                except Rejected:
                    pass  # load shed is a designed outcome, not a bug

        def submitter_b():
            for s in (4, 5):
                try:
                    svc.submit(s, tenant="metered")
                except Rejected:
                    pass

        def prober():
            svc.poll("t00000000")
            svc.stats()
            svc.busy()
            svc.tickets()
            svc.cancel("t00000001")
            svc.poll("t-unknown")

        ts = [concurrency.thread(target=f, name=nm)
              for nm, f in (("driver", driver_role),
                            ("sub-a", submitter_a), ("sub-b", submitter_b),
                            ("probe", prober))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
        svc.close()
    return body


@scenario(
    "churn_storm_vs_serve",
    "The graftchurn mutation plane under exploration: a foreign thread "
    "queues live overlay mutations (grow + a wiring delta, whose "
    "endpoint validation reads the queued-grow total under _cond) and "
    "another submits tickets while the driver-role thread runs "
    "admission ticks whose mutate phase drains the queue — the "
    "mutate/submit/stats interleavings the atomic between-tick "
    "mutation contract promises to serialize.")
def _churn_storm_vs_serve():
    try:
        import jax  # noqa: F401
        from p2pnetwork_tpu.serve.service import (  # noqa: F401
            Rejected, SimService)
        from p2pnetwork_tpu.sim import graph as G
    except Exception as e:  # pragma: no cover - jax-less image
        raise ScenarioUnavailable(f"needs jax/serve: {e}") from e
    g = G.watts_strogatz(24, 4, 0.1, seed=1, source_csr=True)

    def mutations():
        return [("grow", 2),
                ("delta", G.GraphDelta.undirected(add_senders=[24, 25],
                                                  add_receivers=[0, 1]))]

    # Warm OUTSIDE the managed world (the serve_admit_storm rule): the
    # first mutation lazily registers the sim_graph_grow/serve_mutation
    # metric families and compiles the post-churn engine shapes; warmed
    # here, every explored schedule starts compile-hot on raw locks.
    warm = SimService(g, capacity=8, queue_depth=3, chunk_rounds=4, seed=0)
    warm.submit(1)
    for kind, payload in mutations():
        warm.grow(payload) if kind == "grow" else warm.apply_delta(payload)
    warm.tick()
    warm.tick()
    warm.close()

    def body():
        from p2pnetwork_tpu.serve.service import Rejected, SimService
        reg = _fresh_registry()
        svc = watch(SimService(
            g, capacity=8, queue_depth=3, chunk_rounds=4, seed=0,
            registry=reg))

        def driver_role():
            for _ in range(3):
                svc.tick()

        def mutator():
            for kind, payload in mutations():
                if kind == "grow":
                    svc.grow(payload)
                else:
                    svc.apply_delta(payload)

        def submitter():
            for s in (1, 2):
                try:
                    svc.submit(s)
                except Rejected:
                    pass  # load shed is a designed outcome, not a bug

        def prober():
            svc.stats()
            svc.busy()
            svc.tickets()

        ts = [concurrency.thread(target=f, name=nm)
              for nm, f in (("driver", driver_role), ("mutate", mutator),
                            ("submit", submitter), ("probe", prober))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
        svc.close()
    return body


@scenario(
    "sight_scrape_under_serve",
    "The graftsight observability plane under exploration: scraper "
    "threads read /dashboard's document (dashboard_doc, sockets-free), "
    "the Prometheus text, trace exports and the tick-phase profile "
    "while the driver-role thread runs admission ticks through an "
    "armed dispatch fault and its heal retry — every cross-thread "
    "read of the tracer store, SLO rings, phase ring and heal "
    "counters racing the writer that is mid-tick.")
def _sight_scrape_under_serve():
    try:
        import jax  # noqa: F401
        from p2pnetwork_tpu.serve.service import (  # noqa: F401
            Rejected, SimService)
        from p2pnetwork_tpu.sim import graph as G
        from p2pnetwork_tpu.supervise.heal import RetryPolicy
    except Exception as e:  # pragma: no cover - jax-less image
        raise ScenarioUnavailable(f"needs jax/serve: {e}") from e
    g = G.watts_strogatz(24, 4, 0.1, seed=1, source_csr=True)
    # Warm OUTSIDE the managed world, heal path included: a healing
    # service dispatches through the retained-input path, so its engine
    # program (and the registry's process-global sim_* locks) must be
    # compile-hot before any schedule runs (see serve_admit_storm).
    warm = SimService(g, capacity=8, queue_depth=4, chunk_rounds=4, seed=0,
                      heal=RetryPolicy(backoff_base_s=0.0))
    warm.submit(1)
    warm.tick()
    warm.close()

    def body():
        from p2pnetwork_tpu import telemetry
        from p2pnetwork_tpu.chaos import device as chaos_device
        from p2pnetwork_tpu.serve.service import Rejected, SimService
        from p2pnetwork_tpu.supervise.heal import RetryPolicy
        from p2pnetwork_tpu.telemetry import spans
        from p2pnetwork_tpu.telemetry.export import to_prometheus
        from p2pnetwork_tpu.telemetry.httpd import dashboard_doc
        from p2pnetwork_tpu.telemetry.slo import (
            SLOEngine, serve_objectives)
        from p2pnetwork_tpu.utils.logging import EventLog

        reg = _fresh_registry()
        hist = telemetry.History(capacity=32)
        slo = SLOEngine(serve_objectives(slo_rounds=64),
                        registry=reg, log=EventLog())
        tracer = telemetry.Tracer(max_spans=2048)
        prev_tracer = spans.install_tracer(tracer)
        # One preempt at the first dispatch of every schedule: the
        # driver's heal retry runs WHILE the scrapers read, so the
        # fault/heal counters and per-ticket replay race real readers.
        prev_chaos = chaos_device.install_dispatch_chaos(
            chaos_device.DispatchChaos(preempt_at=(0,), registry=reg))
        try:
            svc = watch(SimService(
                g, capacity=8, queue_depth=4, chunk_rounds=4, seed=0,
                heal=RetryPolicy(backoff_base_s=0.0), slo=slo,
                registry=reg))

            def driver_role():
                for _ in range(3):
                    svc.tick()

            def submitter():
                for s in (1, 2, 3):
                    try:
                        svc.submit(s)
                    except Rejected:
                        pass

            def scraper_a():
                # The /dashboard + /metrics scrape path, sockets-free.
                dashboard_doc(reg, hist, tracer, slo, svc)
                to_prometheus(reg)
                slo.snapshot()

            def scraper_b():
                # The /trace + /history scrape path plus the profile.
                tracer.to_chrome()
                tracer.traces()
                hist.snapshot(last=8)
                svc.tick_phases()
                svc.dashboard_slice()

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("driver", driver_role),
                                ("submit", submitter),
                                ("scrape-a", scraper_a),
                                ("scrape-b", scraper_b))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
            svc.close()
        finally:
            chaos_device.install_dispatch_chaos(prev_chaos)
            spans.install_tracer(prev_tracer)
    return body


@scenario(
    "partition_heal",
    "The PR 2 partition-heal soak's control plane under exploration: "
    "partition, concurrent traffic probing link_ok on both sides, heal, "
    "kill/revive — the seeded 8-node soak proves recovery end to end "
    "over real sockets; this proves its ChaosPlane bookkeeping has no "
    "interleaving that tears the partition state.")
def _partition_heal():
    from p2pnetwork_tpu.chaos.plane import ChaosPlane

    def body():
        reg = _fresh_registry()
        plane = watch(ChaosPlane(seed=11, registry=reg))
        side_a = [f"a{i}" for i in range(4)]
        side_b = [f"b{i}" for i in range(4)]

        def splitter():
            plane.partition([side_a, side_b])
            plane.heal_partition()
            plane.partition([side_a[:2] + side_b[:2],
                             side_a[2:] + side_b[2:]])
            plane.heal_partition()

        def traffic():
            for a in side_a[:2]:
                for b in side_b[:2]:
                    plane.link_ok(a, b)
            plane.fault_log()

        def churn():
            plane.kill_nodes([side_b[0]])
            plane.link_ok(side_a[0], side_b[0])
            plane.revive_nodes([side_b[0]])
            plane.cut_links([(side_a[1], side_b[1])])
            plane.heal_links([(side_a[1], side_b[1])])

        ts = [concurrency.thread(target=f, name=nm)
              for nm, f in (("split", splitter), ("traffic", traffic),
                            ("churn", churn))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
    return body


@scenario(
    "journal_vs_close",
    "The graftdur durability plane under exploration: a foreign thread "
    "submits (each acknowledgement is a journal append inside _cond) "
    "while the driver-role thread runs boundary ticks (tick_barrier "
    "fsync + rotate/compact inside _checkpoint), a closer runs the "
    "final-checkpoint close() path, and a promoter fences the trail "
    "via Standby.promote() — the append/close/promote interleavings "
    "where a zombie's publish must die as FencedEpoch, never as a "
    "torn pair or a silently un-journaled acknowledgement.")
def _journal_vs_close():
    try:
        import jax  # noqa: F401
        from p2pnetwork_tpu.serve.service import (  # noqa: F401
            DurabilityLost, FencedEpoch, Rejected, ServiceClosed,
            SimService)
        from p2pnetwork_tpu.serve.standby import Standby  # noqa: F401
        from p2pnetwork_tpu.sim import graph as G
    except Exception as e:  # pragma: no cover - jax-less image
        raise ScenarioUnavailable(f"needs jax/serve: {e}") from e
    g = G.watts_strogatz(24, 4, 0.1, seed=1, source_csr=True)

    # Warm OUTSIDE the managed world (the serve_admit_storm rule): the
    # first journaled service registers the serve_journal_* metric
    # families and compiles the engine shapes; the warm promote
    # additionally compiles the resumed-construction path. Warmed here,
    # every explored schedule starts compile-hot on raw locks.
    warm_dir = tempfile.mkdtemp(prefix="graftrace_dur_warm_")
    try:
        warm = SimService(g, capacity=8, queue_depth=3, chunk_rounds=4,
                          seed=0, store=warm_dir)
        warm.submit(1)
        warm.tick()
        warm_p = Standby(g, warm_dir, capacity=8, queue_depth=3,
                         chunk_rounds=4, seed=0).promote()
        warm_p.close()
        warm.close()
    finally:
        shutil.rmtree(warm_dir, ignore_errors=True)

    def body():
        from p2pnetwork_tpu.serve.service import (
            DurabilityLost, FencedEpoch, Rejected, ServiceClosed,
            SimService)
        from p2pnetwork_tpu.serve.standby import Standby
        reg = _fresh_registry()
        d = tempfile.mkdtemp(prefix="graftrace_dur_")
        try:
            svc = watch(SimService(
                g, capacity=8, queue_depth=3, chunk_rounds=4, seed=0,
                store=d, registry=reg))
            # One published pair before the races: promote() then
            # resumes real state instead of clearing an empty trail.
            svc.submit(1)
            svc.tick()

            def driver_role():
                for _ in range(3):
                    try:
                        svc.tick()
                    except (FencedEpoch, ServiceClosed):
                        # Designed outcomes: the promoter fenced our
                        # boundary publish (we are the zombie now), or
                        # the closer beat us to the driver.
                        return

            def submitter():
                for s in (2, 3):
                    try:
                        svc.submit(s)
                    except (Rejected, ServiceClosed):
                        pass  # shed / post-close submit: designed

            def closer():
                try:
                    svc.close()
                except FencedEpoch:
                    pass  # final checkpoint fenced: the zombie's close

            def promoter():
                reg2 = _fresh_registry()
                promoted = watch(Standby(
                    g, d, capacity=8, queue_depth=3, chunk_rounds=4,
                    seed=0, registry=reg2).promote())
                promoted.close()

            ts = [concurrency.thread(target=f, name=nm)
                  for nm, f in (("driver", driver_role),
                                ("submit", submitter),
                                ("close", closer),
                                ("promote", promoter))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()  # graftlint: ignore[wait-untimed] -- managed-world join: deliberately unbounded so a wedged schedule reports as a graftrace deadlock, not a silent timeout
            try:
                svc.close()
            except FencedEpoch:
                pass  # the promoter owns the trail now
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return body
