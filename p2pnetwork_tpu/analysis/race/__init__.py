"""graftrace: deterministic schedule exploration + happens-before race
detection for the seam-routed thread plane (see sched.py and detector.py
module docstrings; the CLI is ``graftrace`` /
``python -m p2pnetwork_tpu.analysis.race``).

Stdlib-only at import; individual scenarios declare their own heavier
dependencies (the supervise scenario needs jax) and report themselves
unavailable instead of crashing the battery.
"""

from p2pnetwork_tpu.analysis.race.detector import (  # noqa: F401
    DEADLOCK_RULE, ERROR_RULE, RACE_RULE, Detector, Shared, guarded_attrs,
    watch,
)
from p2pnetwork_tpu.analysis.race.sched import (  # noqa: F401
    DeadlockError, RunResult, ScheduleBudgetExceeded, Scheduler,
    TraceProvider, explore, load_replay, write_replay,
)

__all__ = [
    "Detector", "Shared", "watch", "guarded_attrs", "explore",
    "Scheduler", "TraceProvider", "RunResult", "DeadlockError",
    "ScheduleBudgetExceeded", "write_replay", "load_replay",
    "RACE_RULE", "DEADLOCK_RULE", "ERROR_RULE",
]
