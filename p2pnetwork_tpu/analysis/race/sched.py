"""graftrace scheduler: deterministic, replayable thread-interleaving
exploration for the seam-routed concurrency plane.

graftlint (PR 4) reasons about lock discipline from the AST; this module
executes it. The shape is loom/Shuttle for this codebase's thread plane:

- Code under test runs in **managed tasks** — real OS threads whose every
  seam primitive operation (:mod:`p2pnetwork_tpu.concurrency` routed
  through :class:`TraceProvider`) is a *yield point*. Exactly one task
  runs between yield points; at each point the scheduler picks the next
  task, so one seeded run IS one totally-ordered schedule.
- The pick policy is **PCT-style random priorities** (Burckhardt et al.,
  ASPLOS 2010): each task draws a random priority at spawn, the
  highest-priority runnable task runs, and priority-change points
  (classic PCT pre-draws ``d-1`` of them over an estimated length; here
  a seeded per-step coin, so the expected count tracks the actual
  schedule length) redraw a random task's priority — cheap, seedable,
  and effective at surfacing ordering bugs within a handful of seeds.
- Every schedule is a **pure function of its seed**: the trace (one
  ``(task, op, target)`` row per step) is recorded, serializable to a
  replay file, and two runs of the same body under the same seed produce
  byte-identical traces — the property tests/test_graftrace.py pins.

Blocking is modeled, not suffered: a task whose operation cannot proceed
(lock held elsewhere, event unset, queue empty) parks with a wake
predicate; the scheduler never picks it until the predicate holds. When
NOTHING can run, timed waits time out (highest priority first — still
deterministic), and if nothing is timed either, that is a real deadlock:
reported as a P0 finding with every blocked task's site, then unwound by
delivering :class:`DeadlockError` so carrier threads exit.

Wall-clock never enters scheduling decisions — ``sleep`` is a pure yield
point, timeouts fire only at quiescence — so schedules cannot flake on
machine speed.

The scheduler's OWN internals (carrier threads, the per-task handoff
events) must be raw stdlib primitives: instrumenting the instrument
would recurse, hence the inline suppressions.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue as _queue_mod
import random
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.analysis.core import Finding

__all__ = [
    "DeadlockError", "ScheduleBudgetExceeded", "Scheduler",
    "TraceProvider", "RunResult", "explore", "runtime",
    "write_replay", "load_replay",
]

#: Files whose frames are the instrumentation itself, skipped when
#: attributing a yield/access to a source site.
_INTERNAL_FILES = frozenset({"sched.py", "detector.py", "concurrency.py"})


class DeadlockError(RuntimeError):
    """Delivered into every blocked task when the schedule wedged with no
    runnable and no timed-out wait — unwinds the carrier threads."""


class ScheduleBudgetExceeded(RuntimeError):
    """The schedule ran past ``max_steps`` yield points — a livelock (or
    a scenario that polls forever) rather than a terminating body."""


def call_site() -> Tuple[str, int]:
    """(abs file, line) of the nearest frame OUTSIDE the instrumentation
    — the source line a yield point or tracked access belongs to."""
    f = sys._getframe(1)
    while f is not None:
        base = os.path.basename(f.f_code.co_filename)
        if base not in _INTERNAL_FILES:
            return os.path.abspath(f.f_code.co_filename), f.f_lineno
        f = f.f_back
    return "<unknown>", 0


class _Task:
    __slots__ = ("tid", "name", "state", "resume", "priority", "op",
                 "block_check", "timeout_eligible", "deliver", "exc",
                 "thread", "block_site")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.state = "new"       # new|runnable|blocked|running|finished
        # The carrier handoff pair is raw by necessity (module docstring).
        self.resume = threading.Event()  # graftlint: ignore[raw-concurrency-primitive] -- scheduler internals stay raw
        self.priority = 0.0
        self.op: Tuple[str, str] = ("spawn", name)
        self.block_check: Optional[Callable[[], bool]] = None
        self.timeout_eligible = False
        self.deliver: Any = None          # None | "timeout" | BaseException
        self.exc: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None
        self.block_site: Tuple[str, int] = ("<unknown>", 0)


class Scheduler:
    """One seeded exploration of one schedule. See the module docstring
    for the model; use :func:`explore` rather than driving this directly.
    """

    #: Real-time bound on one scheduled step: a managed task that fails
    #: to reach its next yield point in this long called something that
    #: blocks OUTSIDE the seam (a raw lock, a socket) — fail loudly.
    STEP_WALL_TIMEOUT_S = 60.0

    def __init__(self, seed: int = 0, *, detector=None,
                 max_steps: int = 50_000, change_prob: float = 0.1,
                 epsilon: float = 0.25):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.detector = detector
        self.max_steps = int(max_steps)
        #: PCT-style policy knob: per-step probability that one random
        #: task's priority is redrawn. Classic PCT pre-draws d-1 change
        #: points over an estimated schedule length; scenario lengths
        #: here span two orders of magnitude, so a per-step coin (same
        #: seeded stream, still fully deterministic) keeps the expected
        #: change count proportional to the actual length instead of
        #: wasting every change point past the end of a short schedule.
        self.change_prob = float(change_prob)
        #: Exploration knob: probability of scheduling a uniformly random
        #: runnable task instead of the highest-priority one. Priorities
        #: alone drive each task through its whole critical section in
        #: one burst (good for depth), but an AB/BA hazard lives in a
        #: ONE-step window between two acquires — the epsilon picks are
        #: what land inside such windows within a handful of seeds.
        self.epsilon = float(epsilon)
        self.tasks: List[_Task] = []
        self.trace: List[Tuple[str, str, str]] = []
        self.findings: List[Finding] = []
        self.errors: List[Tuple[str, BaseException]] = []
        self.steps = 0
        self._control = threading.Event()  # graftlint: ignore[raw-concurrency-primitive] -- scheduler internals stay raw
        self._tls = threading.local()
        # Deterministic labels for primitives: creation order is itself
        # deterministic under the scheduler, so "lock0"/"event2" name the
        # same object in every run of a seed. Pinned refs keep id() from
        # being recycled onto a different object mid-run.
        self._labels: Dict[int, str] = {}
        self._label_counts: Dict[str, int] = {}
        self._pins: List[Any] = []

    # -------------------------------------------------------------- labels

    def label_for(self, obj: Any, kind: str) -> str:
        key = id(obj)
        lab = self._labels.get(key)
        if lab is None:
            n = self._label_counts.get(kind, 0)
            self._label_counts[kind] = n + 1
            lab = f"{kind}{n}"
            self._labels[key] = lab
            self._pins.append(obj)
        return lab

    # --------------------------------------------------------------- tasks

    def current_task(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    def spawn(self, fn: Callable[[], None], name: Optional[str] = None
              ) -> _Task:
        tid = len(self.tasks)
        task = _Task(tid, name or f"T{tid}")
        task.priority = self.rng.random()
        self.tasks.append(task)
        parent = self.current_task()
        if self.detector is not None:
            self.detector.on_spawn(
                parent.tid if parent is not None else None, tid)

        def _body():
            self._tls.task = task
            # Deliberately unbounded: a carrier legitimately waits its
            # whole (virtual) lifetime for its next turn; the SCHEDULER
            # side bounds every step (STEP_WALL_TIMEOUT_S), which is the
            # end that can actually diagnose a wedge.
            task.resume.wait()  # graftlint: ignore[wait-untimed] -- carrier handoff; the scheduler side is the bounded one
            task.resume.clear()
            try:
                self._deliver(task)
                fn()
            except BaseException as e:  # noqa: BLE001 — reported upward
                task.exc = e
            finally:
                task.state = "finished"
                if self.detector is not None:
                    self.detector.on_finish(task.tid)
                self._control.set()

        t = threading.Thread(  # graftlint: ignore[raw-concurrency-primitive] -- carrier threads ARE the scheduler
            target=_body, name=f"graftrace-{task.name}", daemon=True)
        task.thread = t
        task.state = "runnable"
        t.start()
        return task

    # --------------------------------------------------------- yield point

    def yield_point(self, op: str, target: str = "", *,
                    block_check: Optional[Callable[[], bool]] = None,
                    timeout_eligible: bool = False) -> str:
        """Called by instrumented primitives from a managed task: park
        until scheduled (or until ``block_check`` holds). Returns "ok",
        or "timeout" when a quiescent scheduler expired this task's timed
        wait. Raises whatever the scheduler injected (deadlock unwind).
        Unmanaged threads pass straight through ("external")."""
        task = self.current_task()
        if task is None or task.state == "finished":
            return "external"
        task.op = (op, target)
        task.block_site = call_site()
        task.block_check = block_check
        if block_check is not None and not block_check():
            task.state = "blocked"
            task.timeout_eligible = timeout_eligible
        else:
            task.state = "runnable"
        self._control.set()
        task.resume.wait()  # graftlint: ignore[wait-untimed] -- carrier handoff; the scheduler side is the bounded one
        task.resume.clear()
        return self._deliver(task)

    def _deliver(self, task: _Task) -> str:
        d, task.deliver = task.deliver, None
        task.state = "running"
        task.block_check = None
        task.timeout_eligible = False
        if d == "timeout":
            return "timeout"
        if isinstance(d, BaseException):
            raise d
        return "ok"

    # ----------------------------------------------------------- main loop

    def run(self, body: Callable[[], None]) -> None:
        """Drive ``body`` (as the managed "main" task) and everything it
        spawns to completion under one schedule."""
        main = self.spawn(body, name="main")
        while True:
            runnable = [
                t for t in self.tasks
                if t.state == "runnable"
                or (t.state == "blocked" and t.block_check is not None
                    and t.block_check())
            ]
            if not runnable:
                if all(t.state == "finished" for t in self.tasks):
                    break
                blocked = [t for t in self.tasks if t.state == "blocked"]
                timed = [t for t in blocked if t.timeout_eligible]
                if timed:
                    # Quiescent: fire the highest-priority timed wait —
                    # deterministic, and the only moment "time passes".
                    victim = max(timed, key=lambda t: (t.priority, -t.tid))
                    victim.deliver = "timeout"
                    victim.state = "runnable"
                    continue
                self._report_deadlock(blocked)
                for t in blocked:
                    t.deliver = DeadlockError(
                        f"graftrace: schedule deadlocked at step "
                        f"{self.steps} (seed {self.seed})")
                    t.state = "runnable"
                continue
            self.steps += 1
            if self.steps > self.max_steps:
                self._abort_all()
                raise ScheduleBudgetExceeded(
                    f"graftrace: schedule exceeded {self.max_steps} steps "
                    f"(seed {self.seed}) — livelock or unbounded polling")
            if len(self.tasks) > 1 and self.rng.random() < self.change_prob:
                victim = self.tasks[self.rng.randrange(len(self.tasks))]
                victim.priority = self.rng.random()
            if len(runnable) > 1 and self.rng.random() < self.epsilon:
                nxt = runnable[self.rng.randrange(len(runnable))]
            else:
                nxt = max(runnable, key=lambda t: (t.priority, -t.tid))
            self._step(nxt)
        for t in self.tasks:
            if t.exc is not None and not isinstance(t.exc, DeadlockError):
                self.errors.append((t.name, t.exc))

    def _step(self, task: _Task) -> None:
        self.trace.append((task.name,) + task.op)
        task.state = "running"
        self._control.clear()
        task.resume.set()
        if not self._control.wait(timeout=self.STEP_WALL_TIMEOUT_S):
            raise RuntimeError(
                f"graftrace: task {task.name!r} did not reach a yield "
                f"point within {self.STEP_WALL_TIMEOUT_S}s — it is "
                "blocking outside the seam (raw lock? socket? real "
                "sleep?); route the primitive through "
                "p2pnetwork_tpu.concurrency")

    def _abort_all(self) -> None:
        """Best-effort unwind on budget exhaustion: deliver the abort into
        every parked task so carrier threads exit."""
        for t in self.tasks:
            if t.state in ("blocked", "runnable"):
                t.deliver = ScheduleBudgetExceeded("schedule budget")
                t.resume.set()

    def _report_deadlock(self, blocked: List[_Task]) -> None:
        chain = "; ".join(
            f"{t.name} blocked on {t.op[0]} {t.op[1]}".strip()
            for t in sorted(blocked, key=lambda t: t.tid))
        for t in blocked:
            path, line = t.block_site
            self.findings.append(Finding(
                severity="P0", file=_relpath(path), line=line, col=0,
                rule="graftrace-deadlock",
                message=(f"deadlock: {t.name} blocked on "
                         f"{t.op[0]} {t.op[1]} with no runnable task "
                         f"and no timed wait left ({chain})")))


def _repo_root() -> str:
    # <root>/p2pnetwork_tpu/analysis/race/sched.py -> <root>
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def _relpath(path: str) -> str:
    """Repo-root-relative path for findings (the baseline keys on these);
    files outside the checkout stay absolute rather than growing ../.."""
    try:
        rel = os.path.relpath(os.path.abspath(path), _repo_root())
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


# ------------------------------------------------------ trace primitives
#
# Each primitive mirrors its threading/queue counterpart's call shape but
# resolves every operation through the scheduler. State mutations happen
# only while the owning task is the single running task, so the model
# itself needs no locking for managed use.


class TraceLock:
    _REENTRANT = False

    def __init__(self, sched: Scheduler, det, kind: str = "lock"):
        self._sched = sched
        self._det = det
        self._label = sched.label_for(self, kind)
        self._owner: Optional[int] = None
        self._count = 0

    def _free_for(self, task: _Task) -> bool:
        return self._owner is None or (
            self._REENTRANT and self._owner == task.tid)

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        task = self._sched.current_task()
        if task is None:
            raise RuntimeError(
                "graftrace primitives are confined to managed tasks")
        if not blocking:
            # One scheduling point, then an immediate verdict — a
            # try-acquire never parks.
            self._sched.yield_point("try_acquire", self._label)
            return self._take_if_free(task)
        timed = timeout is not None and timeout >= 0
        while True:
            r = self._sched.yield_point(
                "acquire", self._label,
                block_check=lambda: self._free_for(task),
                timeout_eligible=timed)
            if r == "timeout":
                return False
            if self._take_if_free(task):
                return True

    def _take_if_free(self, task: _Task) -> bool:
        if self._owner == task.tid and self._REENTRANT:
            self._count += 1
            return True
        if self._owner is None:
            self._owner = task.tid
            self._count = 1
            if self._det is not None:
                self._det.on_acquire(task.tid, self._label)
            return True
        return False

    def release(self) -> None:
        task = self._sched.current_task()
        if task is None or self._owner != task.tid:
            raise RuntimeError(
                f"release of {self._label} by a non-owner")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            if self._det is not None:
                self._det.on_release(task.tid, self._label)
        self._sched.yield_point("release", self._label)

    def locked(self) -> bool:
        self._sched.yield_point("locked?", self._label)
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class TraceRLock(TraceLock):
    _REENTRANT = True

    def __init__(self, sched: Scheduler, det):
        super().__init__(sched, det, kind="rlock")


class TraceCondition:
    """Condition variable over a TraceLock (or a fresh one)."""

    def __init__(self, sched: Scheduler, det, lock: Optional[TraceLock] = None):
        self._sched = sched
        self._det = det
        self._lock = lock if lock is not None else TraceLock(sched, det)
        self._label = sched.label_for(self, "cond")
        self._waiting: set = set()   # live, un-notified tickets
        self._notified: set = set()
        self._waiter_seq = 0

    # Lock-protocol passthrough so ``with cond:`` works.
    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self._lock.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        task = self._sched.current_task()
        if task is None or self._lock._owner != task.tid:
            raise RuntimeError("cond.wait without holding its lock")
        ticket = self._waiter_seq = self._waiter_seq + 1
        self._waiting.add(ticket)
        saved, self._lock._count = self._lock._count, 0
        self._lock._owner = None
        if self._det is not None:
            self._det.on_release(task.tid, self._lock._label)
        got = "ok" == self._sched.yield_point(
            "cond_wait", self._label,
            block_check=lambda: ticket in self._notified,
            timeout_eligible=timeout is not None)
        # Retire the ticket permanently (a timed-out waiter included) so
        # notify can never re-spend it on a completed wait.
        self._waiting.discard(ticket)
        self._notified.discard(ticket)
        # Reacquire regardless of outcome (the threading contract).
        while True:
            r = self._sched.yield_point(
                "reacquire", self._lock._label,
                block_check=lambda: self._lock._owner is None)
            if self._lock._owner is None:
                self._lock._owner = task.tid
                self._lock._count = saved
                if self._det is not None:
                    self._det.on_acquire(task.tid, self._lock._label)
                break
            del r
        return got

    def notify(self, n: int = 1) -> None:
        task = self._sched.current_task()
        pending = sorted(self._waiting - self._notified)
        for ticket in pending[:n]:
            self._notified.add(ticket)
        if self._det is not None and task is not None:
            self._det.on_event_set(task.tid, self._label)
        self._sched.yield_point("notify", self._label)

    def notify_all(self) -> None:
        self.notify(n=self._waiter_seq)


class TraceEvent:
    def __init__(self, sched: Scheduler, det):
        self._sched = sched
        self._det = det
        self._label = sched.label_for(self, "event")
        self._flag = False

    def set(self) -> None:
        task = self._sched.current_task()
        self._flag = True
        if self._det is not None and task is not None:
            self._det.on_event_set(task.tid, self._label)
        self._sched.yield_point("set", self._label)

    def clear(self) -> None:
        self._flag = False
        self._sched.yield_point("clear", self._label)

    def is_set(self) -> bool:
        self._sched.yield_point("is_set?", self._label)
        return self._flag

    def wait(self, timeout: Optional[float] = None) -> bool:
        task = self._sched.current_task()
        r = self._sched.yield_point(
            "wait", self._label,
            block_check=lambda: self._flag,
            timeout_eligible=timeout is not None)
        if r == "timeout" and not self._flag:
            return False
        if self._det is not None and task is not None:
            self._det.on_event_wait(task.tid, self._label)
        return True


class TraceQueue:
    """FIFO queue with the stdlib's exception contract; each item carries
    its putter's clock so get() inherits a happens-before edge."""

    def __init__(self, sched: Scheduler, det, maxsize: int = 0):
        self._sched = sched
        self._det = det
        self._label = sched.label_for(self, "queue")
        self._maxsize = int(maxsize)
        self._items: List[Tuple[Any, Any]] = []  # (item, putter clock)

    def _has_room(self) -> bool:
        return self._maxsize <= 0 or len(self._items) < self._maxsize

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        task = self._sched.current_task()
        if not block:
            self._sched.yield_point("try_put", self._label)
            if not self._has_room():
                raise _queue_mod.Full
        else:
            r = self._sched.yield_point(
                "put", self._label, block_check=self._has_room,
                timeout_eligible=timeout is not None)
            if not self._has_room():
                if r == "timeout":
                    raise _queue_mod.Full
                return self.put(item, block, timeout)  # spurious resume
        clock = None
        if self._det is not None and task is not None:
            clock = self._det.on_queue_put(task.tid, self._label)
        self._items.append((item, clock))

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        task = self._sched.current_task()
        if not block:
            self._sched.yield_point("try_get", self._label)
            if not self._items:
                raise _queue_mod.Empty
        else:
            r = self._sched.yield_point(
                "get", self._label,
                block_check=lambda: bool(self._items),
                timeout_eligible=timeout is not None)
            if not self._items:
                if r == "timeout":
                    raise _queue_mod.Empty
                return self.get(block, timeout)  # spurious resume
        item, clock = self._items.pop(0)
        if self._det is not None and task is not None:
            self._det.on_queue_get(task.tid, self._label, clock)
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        self._sched.yield_point("qsize?", self._label)
        return len(self._items)

    def empty(self) -> bool:
        self._sched.yield_point("empty?", self._label)
        return not self._items

    def task_done(self) -> None:  # join() accounting is not modeled
        pass


class TraceThread:
    """The threading.Thread call-shape subset the repo uses, running the
    target as a managed task."""

    def __init__(self, sched: Scheduler, det, target=None, name=None,
                 args=(), kwargs=None, daemon=None):
        self._sched = sched
        self._det = det
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        # An unnamed thread gets its spawn-order name ("T<tid>") at
        # start(): any per-run-independent counter here would leak
        # process history into trace task names and break the
        # same-seed-byte-identical replay contract.
        self.name = name
        self.daemon = bool(daemon)
        self._task: Optional[_Task] = None

    def _run(self) -> None:
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def start(self) -> None:
        if self._task is not None:
            raise RuntimeError("threads can only be started once")
        self._task = self._sched.spawn(self._run, name=self.name)
        self.name = self._task.name  # resolves the T<tid> default
        self._sched.yield_point("start", self.name)

    def join(self, timeout: Optional[float] = None) -> None:
        task = self._sched.current_task()
        child = self._task
        if child is None:
            return
        r = self._sched.yield_point(
            "join", child.name,
            block_check=lambda: child.state == "finished",
            timeout_eligible=timeout is not None)
        if child.state == "finished" and r != "timeout" \
                and self._det is not None and task is not None:
            self._det.on_join(task.tid, child.tid)

    def is_alive(self) -> bool:
        self._sched.yield_point("is_alive?", self.name or "unstarted")
        return self._task is not None and self._task.state != "finished"


class TraceProvider:
    """The :mod:`p2pnetwork_tpu.concurrency` provider graftrace installs:
    every factory returns the instrumented counterpart bound to one
    scheduler/detector pair."""

    def __init__(self, sched: Scheduler, det=None):
        self._sched = sched
        self._det = det if det is not None else sched.detector

    def lock(self):
        return TraceLock(self._sched, self._det)

    def rlock(self):
        return TraceRLock(self._sched, self._det)

    def condition(self, lock=None):
        return TraceCondition(self._sched, self._det, lock)

    def event(self):
        return TraceEvent(self._sched, self._det)

    def thread(self, target=None, name=None, args=(), kwargs=None,
               daemon=None):
        return TraceThread(self._sched, self._det, target=target,
                           name=name, args=args, kwargs=kwargs,
                           daemon=daemon)

    def fifo_queue(self, maxsize: int = 0):
        return TraceQueue(self._sched, self._det, maxsize)

    def sleep(self, seconds: float) -> None:
        # Virtual: a pure scheduling point. No wall time passes, so a
        # schedule can never flake on machine speed.
        self._sched.yield_point("sleep", f"{seconds:g}")


# ------------------------------------------------------------ run driver

_active_lock = threading.Lock()  # graftlint: ignore[raw-concurrency-primitive] -- guards the runtime swap itself
_active: Optional[Tuple[Scheduler, Any]] = None


def runtime() -> Optional[Tuple[Scheduler, Any]]:
    """The (scheduler, detector) of the exploration in flight, if any —
    how Shared cells and watched objects find their reporting sink."""
    with _active_lock:
        return _active


@dataclasses.dataclass
class RunResult:
    """One explored schedule: its seed, trace, findings and errors."""

    seed: int
    steps: int
    trace: List[Tuple[str, str, str]]
    findings: List[Finding]
    errors: List[Tuple[str, str]]
    #: The budget the schedule ran under — recorded into replay files so
    #: a schedule explored with a raised budget replays under the same.
    max_steps: int = 50_000

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def trace_lines(self) -> List[str]:
        return [" ".join(row).rstrip() for row in self.trace]


def explore(body: Callable[[], None], *, seed: int = 0,
            max_steps: int = 50_000, change_prob: float = 0.1,
            epsilon: float = 0.25, detector=None) -> RunResult:
    """Run ``body`` once under the deterministic scheduler with ``seed``.

    ``body`` executes as the managed main task with the TraceProvider
    installed on the concurrency seam: every primitive it (or the
    library code it drives) constructs through the seam is instrumented,
    every spawned ``concurrency.thread`` becomes a managed task, and the
    detector accumulates happens-before state. Returns the
    :class:`RunResult`; same body + same seed ⇒ identical trace and
    findings (the replay contract).
    """
    global _active
    if detector is None:
        from p2pnetwork_tpu.analysis.race.detector import Detector
        detector = Detector()
    sched = Scheduler(seed=seed, detector=detector, max_steps=max_steps,
                      change_prob=change_prob, epsilon=epsilon)
    provider = TraceProvider(sched, detector)
    with _active_lock:
        if _active is not None:
            raise RuntimeError("explore() does not nest")
        _active = (sched, detector)
    prev = concurrency.install(provider)
    try:
        sched.run(body)
    finally:
        concurrency.install(prev)
        with _active_lock:
            _active = None
    findings = sorted(set(detector.findings) | set(sched.findings))
    errors = [(name, f"{type(e).__name__}: {e}")
              for name, e in sched.errors]
    return RunResult(seed=seed, steps=sched.steps, trace=list(sched.trace),
                     findings=findings, errors=errors, max_steps=max_steps)


# ------------------------------------------------------------ replay I/O

def write_replay(path: str, scenario: str, result: RunResult) -> str:
    """Persist one schedule so a failing interleaving reruns from its
    seed: the seed is the authority, the recorded trace is the oracle a
    replay is checked byte-for-byte against."""
    doc = {
        "scenario": scenario,
        "seed": result.seed,
        "steps": result.steps,
        "max_steps": result.max_steps,
        "trace": [list(row) for row in result.trace],
        "findings": [f.to_json() for f in result.findings],
        "errors": list(result.errors),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return path


def load_replay(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if "scenario" not in doc or "seed" not in doc or "trace" not in doc:
        raise ValueError(f"{path}: not a graftrace replay file")
    return doc
