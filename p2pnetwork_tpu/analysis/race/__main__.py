"""graftrace CLI: ``python -m p2pnetwork_tpu.analysis.race [options]``.

The dynamic third of the analysis gate (graftlint = source AST,
graftaudit = compiled IR, graftrace = executed schedules): run every
builtin scenario across K seeded schedules, report races/deadlocks as
findings through the shared severity/baseline/suppression machinery, and
exit nonzero on anything not baselined. Exit codes match graftlint:
0 — clean; 1 — findings to fix; 2 — bad invocation or a replay that
diverged (nondeterminism is itself a failure).

Typical invocations::

    graftrace                                   # the CI gate
    graftrace --seed 7 --schedules 16           # dig at one seed range
    graftrace --scenario phi_quarantine --trace-dir /tmp/traces
    graftrace --replay /tmp/traces/phi_quarantine_s7.json
    graftrace --scenarios-from my_scenarios.py --scenario my_storm
    graftrace --list-scenarios

Replay workflow: a failing schedule written with ``--trace-dir`` reruns
byte-identically from its seed; ``--replay FILE`` re-executes it and
verifies the recorded trace step for step before reporting the findings.

Telemetry: every explored schedule counts into
``graftrace_schedules_total`` and every distinct race into
``graftrace_races_total{rule}`` in the default registry.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.analysis import core
from p2pnetwork_tpu.analysis.race import scenarios as scen
from p2pnetwork_tpu.analysis.race import sched as _sched
from p2pnetwork_tpu.analysis.race.sched import (
    explore, load_replay, write_replay,
)

DEFAULT_SCHEDULES = 8


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftrace",
        description=("Deterministic schedule exploration + happens-before "
                     "race detection over the seam-routed thread plane. "
                     "Zero non-baselined findings is the CI gate."))
    p.add_argument("--seed", type=int, default=0,
                   help="first schedule seed (default 0)")
    p.add_argument("--schedules", type=int, default=DEFAULT_SCHEDULES,
                   metavar="K",
                   help=f"seeded schedules per scenario (seed..seed+K-1; "
                        f"default {DEFAULT_SCHEDULES})")
    p.add_argument("--scenario", action="append", default=None,
                   metavar="NAME",
                   help="run only this scenario (repeatable)")
    p.add_argument("--scenarios-from", default=None, metavar="FILE",
                   help="import a python file registering extra scenarios "
                        "(they join --scenario selection, not the default "
                        "battery)")
    p.add_argument("--replay", default=None, metavar="FILE",
                   help="re-run the schedule recorded in FILE from its "
                        "seed and verify the trace is byte-identical "
                        "before reporting its findings")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="write a replay file for every schedule that "
                        "produced findings")
    p.add_argument("--max-steps", type=int, default=50_000,
                   help="per-schedule step budget (livelock bound)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (one JSON document)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: the package's checked-in "
                        "analysis/race/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report grandfathered findings too (exit code "
                        "still keys on non-baselined ones)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather every current finding and exit 0 "
                        "(races found during development should be FIXED, "
                        "not baselined — this exists for annotating "
                        "refuted hazards and for bootstrap)")
    p.add_argument("--list-scenarios", action="store_true",
                   help="print the scenario table and exit")
    return p


def _load_scenarios_file(path: str) -> None:
    spec = importlib.util.spec_from_file_location(
        f"_graftrace_scenarios_{abs(hash(path))}", path)
    if spec is None or spec.loader is None:
        raise FileNotFoundError(path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)


def _select(names: Optional[List[str]]) -> List[str]:
    if names is None:
        return scen.builtin_names()
    unknown = [n for n in names if n not in scen.SCENARIOS]
    if unknown:
        raise SystemExit(
            f"graftrace: unknown scenario(s): {', '.join(unknown)} "
            "(try --list-scenarios)")
    return list(names)


def _modules_for(findings: List[core.Finding]
                 ) -> Dict[str, core.Module]:
    """Parse each flagged file once so suppressions and baseline
    fingerprints see the same Module view graftlint would."""
    root = _sched._repo_root()
    out: Dict[str, core.Module] = {}
    for f in findings:
        if f.file in out:
            continue
        path = f.file if os.path.isabs(f.file) \
            else os.path.join(root, f.file)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                out[f.file] = core.Module(path, fh.read(), relpath=f.file)
        except (OSError, SyntaxError, ValueError):
            continue  # unsuppressable, unfingerprintable — stays gated
    return out


def run_battery(names: List[str], *, seed: int, schedules: int,
                max_steps: int = 50_000, trace_dir: Optional[str] = None,
                registry: Optional[telemetry.Registry] = None,
                ) -> Tuple[List[core.Finding], List[dict]]:
    """Explore each scenario across ``schedules`` seeds; returns the
    deduplicated findings and per-scenario stats (the library entry the
    CLI and tests share)."""
    reg = registry if registry is not None else telemetry.default_registry()
    m_sched = reg.counter(
        "graftrace_schedules_total",
        "Seeded schedules explored by graftrace.")
    m_races = reg.counter(
        "graftrace_races_total",
        "Distinct graftrace findings, by rule.", ("rule",))
    all_findings: List[core.Finding] = []
    seen_keys = set()
    stats: List[dict] = []
    for name in names:
        entry = scen.SCENARIOS[name]
        row = {"scenario": name, "schedules": 0, "steps": 0,
               "findings": 0, "errors": [], "skipped": None}
        try:
            entry.factory()  # availability probe (imports, deps)
        except scen.ScenarioUnavailable as e:
            row["skipped"] = str(e)
            stats.append(row)
            continue
        for s in range(seed, seed + schedules):
            body = entry.factory()
            try:
                result = explore(body, seed=s, max_steps=max_steps)
            except Exception as e:
                # A livelocked schedule (ScheduleBudgetExceeded) or a
                # raw-blocking wedge (the step wall timeout) is a
                # verdict on that scenario, not a reason to abandon the
                # rest of the battery with a traceback.
                m_sched.inc()
                row["schedules"] += 1
                row["errors"].append({"seed": s, "task": "<scheduler>",
                                      "error": f"{type(e).__name__}: {e}"})
                f = core.Finding(
                    severity="P1", file=f"<scenario:{name}>", line=0,
                    col=0, rule="graftrace-error",
                    message=(f"schedule aborted: {type(e).__name__}: "
                             f"{e} (seed {s})"))
                key = (f.rule, f.file, f.line, f.message)
                if key not in seen_keys:
                    seen_keys.add(key)
                    all_findings.append(f)
                    m_races.labels(f.rule).inc()
                    row["findings"] += 1
                continue
            m_sched.inc()
            row["schedules"] += 1
            row["steps"] += result.steps
            for name_err in result.errors:
                row["errors"].append({"seed": s, "task": name_err[0],
                                      "error": name_err[1]})
                all_findings.append(core.Finding(
                    severity="P1", file=f"<scenario:{name}>", line=0,
                    col=0, rule="graftrace-error",
                    message=(f"task {name_err[0]} raised "
                             f"{name_err[1]} (seed {s})")))
            fresh = []
            for f in result.findings:
                key = (f.rule, f.file, f.line, f.message)
                if key in seen_keys:
                    continue
                seen_keys.add(key)
                fresh.append(f)
                m_races.labels(f.rule).inc()
            row["findings"] += len(fresh)
            all_findings.extend(fresh)
            if trace_dir and (result.findings or result.errors):
                os.makedirs(trace_dir, exist_ok=True)
                write_replay(
                    os.path.join(trace_dir, f"{name}_s{s}.json"),
                    name, result)
        stats.append(row)
    return sorted(set(all_findings)), stats


def _replay(path: str, as_json: bool) -> int:
    doc = load_replay(path)
    name = doc["scenario"]
    if name not in scen.SCENARIOS:
        print(f"graftrace: replay names unknown scenario {name!r}",
              file=sys.stderr)
        return 2
    body = scen.SCENARIOS[name].factory()
    result = explore(body, seed=int(doc["seed"]),
                     max_steps=int(doc.get("max_steps", 50_000)))
    recorded = [tuple(row) for row in doc["trace"]]
    if recorded != result.trace:
        divergence = next(
            (i for i, (a, b) in enumerate(zip(recorded, result.trace))
             if a != b), min(len(recorded), len(result.trace)))
        print(f"graftrace: REPLAY DIVERGED at step {divergence} "
              f"(recorded {len(recorded)} steps, got "
              f"{len(result.trace)}) — the scenario is nondeterministic, "
              "which is itself a bug", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps({
            "scenario": name, "seed": doc["seed"], "replayed": True,
            "identical": True,
            "findings": [f.to_json() for f in result.findings],
            "errors": list(result.errors),
        }, indent=1))
    else:
        print(f"graftrace: replay of {name} seed {doc['seed']} is "
              f"byte-identical ({len(result.trace)} steps)")
        for f in result.findings:
            print(f.render())
        for task_name, err in result.errors:
            print(f"error: task {task_name} raised {err}")
    # Errors fail a replay exactly like findings do: run_battery gated
    # (and recorded) this schedule because of them.
    return 1 if (result.findings or result.errors) else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.scenarios_from:
        try:
            _load_scenarios_file(args.scenarios_from)
        except Exception as e:
            # Any failure loading the user's file — missing, unreadable,
            # syntax error, crash at import — is a bad invocation, not a
            # traceback: the documented exit-2 class.
            print(f"graftrace: cannot load {args.scenarios_from}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    if args.list_scenarios:
        width = max((len(n) for n in scen.SCENARIOS), default=10)
        for name, entry in sorted(scen.SCENARIOS.items()):
            tag = "" if entry.builtin else "  [extra]"
            print(f"{name:<{width}}  {entry.doc}{tag}")
        return 0

    if args.replay:
        try:
            return _replay(args.replay, args.as_json)
        except (OSError, ValueError) as e:
            print(f"graftrace: {e}", file=sys.stderr)
            return 2

    if args.schedules < 1:
        print("graftrace: --schedules must be >= 1", file=sys.stderr)
        return 2

    try:
        names = _select(args.scenario)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    findings, stats = run_battery(
        names, seed=args.seed, schedules=args.schedules,
        max_steps=args.max_steps, trace_dir=args.trace_dir)

    modules = _modules_for(findings)
    suppressed = [f for f in findings
                  if f.file in modules and modules[f.file].suppressed(f)]
    gated = [f for f in findings
             if not (f.file in modules and modules[f.file].suppressed(f))]

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        kept: Dict = {}
        path = core.write_baseline(gated, modules, baseline_path,
                                   keep=kept)
        print(f"graftrace: wrote {len(gated)} finding(s) to {path}")
        return 0

    baseline = core.load_baseline(baseline_path)
    new, grandfathered = core.apply_baseline(gated, modules, baseline)

    skipped = [s for s in stats if s["skipped"]]
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": ([f.to_json() for f in grandfathered]
                          if args.no_baseline else len(grandfathered)),
            "suppressed": len(suppressed),
            "scenarios": stats,
            "ok": not new,
        }, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if args.no_baseline and grandfathered:
        print(f"-- {len(grandfathered)} baselined finding(s):")
        for f in grandfathered:
            print("   " + f.render())
    for s in skipped:
        print(f"-- skipped {s['scenario']}: {s['skipped']}")
    n_sched = sum(s["schedules"] for s in stats)
    n_steps = sum(s["steps"] for s in stats)
    if new:
        print(f"graftrace: {len(new)} finding(s) over {n_sched} "
              f"schedule(s); {len(grandfathered)} baselined")
        return 1
    suffix = f" ({len(grandfathered)} baselined)" if grandfathered else ""
    print(f"graftrace: clean{suffix} — {len(stats) - len(skipped)} "
          f"scenario(s), {n_sched} schedule(s), {n_steps} steps")
    return 0


def _cli() -> int:
    try:
        return main()
    except BrokenPipeError:
        return 0


if __name__ == "__main__":
    sys.exit(_cli())
