"""graftrace detector: vector-clock happens-before race detection over
one explored schedule.

The scheduler (sched.py) serializes managed tasks; this module decides
which of the serialized accesses were ordered by *synchronization* and
which merely by the coin flip of the schedule. Standard vector-clock
happens-before (FastTrack's epoch comparison, without its shadow-word
compression — schedules here are test-sized):

- every task carries a clock ``{tid: count}``, ticked per operation;
- **release → acquire**: a lock stores its releaser's clock; an acquirer
  joins it — two critical sections of one lock are always ordered;
- **start / join**: a spawned task inherits its parent's clock; a join
  folds the child's final clock back into the joiner;
- **event set → wait**: an event accumulates every setter's clock; a
  successful wait joins it (conditions' notify/wait map to the same
  edge);
- **queue put → get**: each item carries its putter's clock; the getter
  joins it.

Tracked shared state is declared, not inferred at runtime: either
explicitly (:class:`Shared` cells, the fixture-grade form with exact
source lines) or by :func:`watch`, which auto-tracks the attributes
graftlint's lock model already inventories as lock-guarded (an attribute
somewhere mutated under a held lock) on any instance — intercepting
reads/writes via a generated subclass, with container values wrapped so
``d[k] = v`` counts as the write it is. Two conflicting accesses (at
least one write) whose clocks are unordered are a race: reported as a
P0 :class:`~p2pnetwork_tpu.analysis.core.Finding` at the racing access's
``file:line``, naming both sites and both held locksets, flowing through
the same severity/baseline/suppression machinery as graftlint.

Soundness note: in the OBSERVED schedule, HB detection has no false
positives — accesses consistently guarded by any one lock are always
ordered through that lock's clock. Accumulated event clocks and the
explored-schedule set bound the false-*negative* rate; that is what
``--schedules K`` buys down.
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from p2pnetwork_tpu.analysis import core
from p2pnetwork_tpu.analysis.concurrency import _concurrency
from p2pnetwork_tpu.analysis.core import Finding, Module
from p2pnetwork_tpu.analysis.race import sched as _sched

__all__ = ["Detector", "Shared", "watch", "guarded_attrs",
           "RACE_RULE", "DEADLOCK_RULE", "ERROR_RULE"]

RACE_RULE = "graftrace-race"
DEADLOCK_RULE = "graftrace-deadlock"
ERROR_RULE = "graftrace-error"

#: Container methods that mutate in place — the same vocabulary
#: graftlint's lock model uses to classify guarded-state writes.
from p2pnetwork_tpu.analysis.concurrency import _MUTATORS as _WRITE_METHODS


# ------------------------------------------------------------ vector clocks

def _join(into: Dict[int, int], other: Dict[int, int]) -> None:
    for tid, c in other.items():
        if c > into.get(tid, 0):
            into[tid] = c


def _ordered_before(epoch: Tuple[int, int], clock: Dict[int, int]) -> bool:
    """Did the access at ``epoch = (tid, count)`` happen-before a task
    whose current clock is ``clock``? The standard epoch test."""
    tid, count = epoch
    return count <= clock.get(tid, 0)


class _Access:
    __slots__ = ("tid", "epoch", "site", "lockset", "is_write")

    def __init__(self, tid: int, epoch: Tuple[int, int],
                 site: Tuple[str, int], lockset: FrozenSet[str],
                 is_write: bool):
        self.tid = tid
        self.epoch = epoch
        self.site = site
        self.lockset = lockset
        self.is_write = is_write


class Detector:
    """Happens-before state for one schedule; the scheduler drives the
    ``on_*`` hooks, tracked state drives :meth:`access`."""

    def __init__(self):
        self.clocks: Dict[int, Dict[int, int]] = {}
        self.locksets: Dict[int, Set[str]] = {}
        self.lock_clocks: Dict[str, Dict[int, int]] = {}
        self.event_clocks: Dict[str, Dict[int, int]] = {}
        self.finish_clocks: Dict[int, Dict[int, int]] = {}
        # var key -> (last write, reads since that write)
        self.vars: Dict[str, Tuple[Optional[_Access], List[_Access]]] = {}
        self.findings: List[Finding] = []
        self._reported: Set[Tuple] = set()
        self._task_names: Dict[int, str] = {}

    # ------------------------------------------------------ schedule hooks

    def _tick(self, tid: int) -> None:
        clock = self.clocks.setdefault(tid, {tid: 0})
        clock[tid] = clock.get(tid, 0) + 1

    def on_spawn(self, parent: Optional[int], tid: int) -> None:
        clock = dict(self.clocks.get(parent, {})) if parent is not None \
            else {}
        clock[tid] = 1
        self.clocks[tid] = clock
        self.locksets[tid] = set()
        if parent is not None:
            self._tick(parent)

    def on_finish(self, tid: int) -> None:
        self.finish_clocks[tid] = dict(self.clocks.get(tid, {}))

    def on_join(self, tid: int, child: int) -> None:
        _join(self.clocks.setdefault(tid, {tid: 0}),
              self.finish_clocks.get(child, self.clocks.get(child, {})))
        self._tick(tid)

    def on_acquire(self, tid: int, label: str) -> None:
        _join(self.clocks.setdefault(tid, {tid: 0}),
              self.lock_clocks.get(label, {}))
        self.locksets.setdefault(tid, set()).add(label)
        self._tick(tid)

    def on_release(self, tid: int, label: str) -> None:
        self._tick(tid)
        self.lock_clocks[label] = dict(self.clocks.get(tid, {}))
        self.locksets.setdefault(tid, set()).discard(label)

    def on_event_set(self, tid: int, label: str) -> None:
        self._tick(tid)
        _join(self.event_clocks.setdefault(label, {}),
              self.clocks.get(tid, {}))

    def on_event_wait(self, tid: int, label: str) -> None:
        _join(self.clocks.setdefault(tid, {tid: 0}),
              self.event_clocks.get(label, {}))
        self._tick(tid)

    def on_queue_put(self, tid: int, label: str) -> Dict[int, int]:
        self._tick(tid)
        return dict(self.clocks.get(tid, {}))

    def on_queue_get(self, tid: int, label: str,
                     clock: Optional[Dict[int, int]]) -> None:
        if clock:
            _join(self.clocks.setdefault(tid, {tid: 0}), clock)
        self._tick(tid)

    # ------------------------------------------------------------- accesses

    def access(self, tid: int, var: str, is_write: bool,
               site: Tuple[str, int]) -> None:
        """One read/write of tracked variable ``var`` by task ``tid`` at
        ``site``; checks it against every conflicting prior access not
        ordered before the current clock."""
        clock = self.clocks.setdefault(tid, {tid: 0})
        self._tick(tid)
        cur = _Access(tid, (tid, clock[tid]), site,
                      frozenset(self.locksets.get(tid, ())), is_write)
        last_write, reads = self.vars.get(var, (None, []))
        if last_write is not None and last_write.tid != tid \
                and not _ordered_before(last_write.epoch, clock):
            self._report(var, last_write, cur)
        if is_write:
            for r in reads:
                if r.tid != tid and not _ordered_before(r.epoch, clock):
                    self._report(var, r, cur)
            self.vars[var] = (cur, [])
        else:
            # One live read per task is enough: a newer read of the same
            # task supersedes the older for HB purposes.
            reads = [r for r in reads if r.tid != tid] + [cur]
            self.vars[var] = (last_write, reads)

    def _report(self, var: str, prev: _Access, cur: _Access) -> None:
        key = (var, prev.site, cur.site, prev.is_write, cur.is_write)
        if key in self._reported:
            return
        self._reported.add(key)
        path, line = cur.site
        pfile, pline = prev.site
        verb = "write" if cur.is_write else "read"
        pverb = "write" if prev.is_write else "read"
        locks = ",".join(sorted(cur.lockset)) or "no locks"
        plocks = ",".join(sorted(prev.lockset)) or "no locks"
        self.findings.append(Finding(
            severity="P0", file=_sched._relpath(path), line=line, col=0,
            rule=RACE_RULE,
            message=(f"unordered {verb} of {var} (held: {locks}) races "
                     f"a {pverb} at {_sched._relpath(pfile)}:{pline} "
                     f"(held: {plocks}) — no happens-before edge "
                     "(lock, start/join, event, queue) orders them")))


# ---------------------------------------------------------------- Shared

class Shared:
    """An explicitly declared shared cell — the ``track()`` primitive in
    its simplest form. ``get``/``set`` are scheduling points and tracked
    accesses, so the racy fixture's ``cell.set(...)`` line is exactly
    where a finding anchors. Outside an exploration it is just a box."""

    __slots__ = ("_value", "_label")

    def __init__(self, value: Any = None, label: Optional[str] = None):
        self._value = value
        self._label = str(label) if label is not None else None

    def _var(self) -> str:
        # Unlabeled cells resolve to a per-object creation-order label
        # ("shared0", "shared1", ...) under the active scheduler:
        # keying two distinct cells on one literal would alias them into
        # a single detector variable and fabricate races between
        # unrelated data.
        if self._label is not None:
            return self._label
        rt = _sched.runtime()
        if rt is None:
            return "shared"
        return rt[0].label_for(self, "shared")

    def get(self) -> Any:
        _report_access(self._var(), False)
        return self._value

    def set(self, value: Any) -> None:
        _report_access(self._var(), True)
        self._value = value


def _report_access(var: str, is_write: bool) -> None:
    rt = _sched.runtime()
    if rt is None:
        return
    scheduler, det = rt
    task = scheduler.current_task()
    if task is None:
        return
    site = _sched.call_site()
    scheduler.yield_point("write" if is_write else "read", var)
    det.access(task.tid, var, is_write, site)


# ----------------------------------------------------------------- watch

#: Parsed-module cache for guarded-attribute inference (keyed by file).
_module_cache: Dict[str, Optional[Module]] = {}  # graftlint: ignore[unbounded-cache] -- keyed by source file path; bounded by the finite set of modules the process imports


def _module_for(cls: type) -> Optional[Module]:
    try:
        path = inspect.getsourcefile(cls)
    except TypeError:
        return None
    if path is None:
        return None
    path = os.path.abspath(path)
    if path not in _module_cache:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            _module_cache[path] = Module(path, source,
                                         relpath=_sched._relpath(path))
        except (OSError, SyntaxError, ValueError):
            _module_cache[path] = None
    return _module_cache[path]


def guarded_attrs(cls: type) -> Dict[str, Set[str]]:
    """``{attr: {lock ids}}`` for every attribute some method of ``cls``
    (or an ancestor) mutates while holding a lock — the same inventory
    graftlint's lock-guard rule builds, reused as the auto-tracking set.
    Lock attributes themselves are excluded (they are the guards)."""
    out: Dict[str, Set[str]] = {}
    for klass in cls.__mro__:
        if klass is object:
            continue
        module = _module_for(klass)
        if module is None:
            continue
        conc = _concurrency(module)
        lock_attrs = set(conc.class_locks.get(klass.__name__, ()))
        for summary in conc.summaries.values():
            if summary.class_name != klass.__name__:
                continue
            for attr, _site, held, mutation in summary.attr_access:
                if mutation and held and attr not in lock_attrs:
                    out.setdefault(attr, set()).update(held)
    return out


class _TrackedContainer:
    """Wraps a container value of a watched attribute so its operations
    report as reads/writes of that attribute (``d[k] = v`` through the
    attribute is a write of the guarded state, which plain
    ``__getattribute__`` interception would misread as a read)."""

    __slots__ = ("_obj", "_var")

    def __init__(self, obj: Any, var: str):
        object.__setattr__(self, "_obj", obj)
        object.__setattr__(self, "_var", var)

    def __getattr__(self, name: str) -> Any:
        obj = object.__getattribute__(self, "_obj")
        var = object.__getattribute__(self, "_var")
        target = getattr(obj, name)
        if callable(target):
            is_write = name in _WRITE_METHODS

            def call(*a, **k):
                _report_access(var, is_write)
                return target(*a, **k)
            return call
        _report_access(var, False)
        return target

    def _read(self):
        _report_access(object.__getattribute__(self, "_var"), False)
        return object.__getattribute__(self, "_obj")

    def _write(self):
        _report_access(object.__getattribute__(self, "_var"), True)
        return object.__getattribute__(self, "_obj")

    def __getitem__(self, k):
        return self._read()[k]

    def __setitem__(self, k, v):
        self._write()[k] = v

    def __delitem__(self, k):
        del self._write()[k]

    def __contains__(self, k):
        return k in self._read()

    def __iter__(self):
        return iter(self._read())

    def __len__(self):
        return len(self._read())

    def __bool__(self):
        return bool(self._read())

    def __eq__(self, other):
        return self._read() == other

    def __ne__(self, other):
        return self._read() != other

    def __repr__(self):
        return repr(object.__getattribute__(self, "_obj"))

    def __hash__(self):
        return hash(object.__getattribute__(self, "_obj"))

    def __ior__(self, other):  # set |= / tombs |= ...
        obj = self._write()
        obj |= other
        object.__setattr__(self, "_obj", obj)
        return self


import collections as _collections

#: Container values of watched attributes get the mutation-aware proxy.
#: deque matters: EventLog and phi's arrival windows are deque-backed,
#: and an unwrapped deque's append would classify as a read — exactly
#: the "deque mutated during iteration" race class going invisible.
_CONTAINER_TYPES = (dict, list, set, _collections.deque)


def watch(obj: Any, attrs: Optional[Set[str]] = None,
          label: Optional[str] = None) -> Any:
    """Auto-track ``obj``'s lock-guarded attributes (or an explicit
    ``attrs`` set) for the active exploration, in place.

    The instance's class is swapped for a generated subclass whose
    ``__getattribute__``/``__setattr__`` report tracked accesses to the
    detector (each a scheduling point) before delegating; container
    values come back wrapped so mutations classify as writes. Returns
    ``obj`` for chaining. A no-op set of attrs leaves the object
    untouched."""
    if getattr(type(obj), "_graftrace_tracked", None) is not None:
        return obj  # already watched — idempotent
    tracked = set(attrs) if attrs is not None else \
        set(guarded_attrs(type(obj)))
    if not tracked:
        return obj
    rt = _sched.runtime()
    if rt is None:
        return obj
    scheduler, _det = rt
    base = type(obj)
    prefix = label if label is not None else \
        scheduler.label_for(obj, base.__name__)
    tracked_fs = frozenset(tracked)

    def var_of(name: str) -> str:
        return f"{prefix}.{name}"

    def __getattribute__(self, name):
        value = base.__getattribute__(self, name)
        if name in tracked_fs:
            _report_access(var_of(name), False)
            if isinstance(value, _CONTAINER_TYPES):
                return _TrackedContainer(value, var_of(name))
        return value

    def __setattr__(self, name, value):
        if name in tracked_fs:
            _report_access(var_of(name), True)
        base.__setattr__(self, name, value)

    watched = type(f"Watched{base.__name__}", (base,), {
        "__slots__": (),
        "__getattribute__": __getattribute__,
        "__setattr__": __setattr__,
        "_graftrace_tracked": tracked_fs,
    })
    obj.__class__ = watched
    return obj
