"""graftlint CLI: ``python -m p2pnetwork_tpu.analysis [paths...]``.

Exit codes: 0 — no non-baselined findings; 1 — findings to fix; 2 — bad
invocation. Stdlib-only, so the gate runs in a sockets-only environment
(no jax) and costs sub-second wall time on the whole package.

Typical invocations::

    python -m p2pnetwork_tpu.analysis p2pnetwork_tpu/   # the CI gate
    python -m p2pnetwork_tpu.analysis --json some/file.py
    python -m p2pnetwork_tpu.analysis --no-baseline p2pnetwork_tpu/
    python -m p2pnetwork_tpu.analysis --write-baseline p2pnetwork_tpu/
    python -m p2pnetwork_tpu.analysis --list-rules
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from p2pnetwork_tpu.analysis import core


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description=("AST analysis for JAX retrace/sync hazards and lock "
                     "discipline. Zero non-baselined findings is the CI "
                     "gate; suppress judged-acceptable sites inline with "
                     "`# graftlint: ignore[rule-id] -- rationale`."))
    p.add_argument("paths", nargs="*", default=["p2pnetwork_tpu"],
                   help="files or directories to analyze "
                        "(default: p2pnetwork_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (one JSON document)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: the package's checked-in "
                        "analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report grandfathered findings too (exit code "
                        "still keys on non-baselined ones)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather every current finding into the "
                        "baseline file and exit 0 (refused with --rules/"
                        "--severity: a filtered run must not overwrite "
                        "other rules' grandfathered entries)")
    p.add_argument("--no-suppressions", action="store_true",
                   help="report inline-suppressed findings as well "
                        "(audit mode; does not affect the exit code)")
    p.add_argument("--root", default=None, metavar="DIR",
                   help="directory reported file paths (and baseline "
                        "entries) are relative to; default: this "
                        "package's repository root when it contains "
                        "every analyzed path, else the current directory "
                        "— so the gate matches its baseline from any cwd")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help="run only these rule ids")
    p.add_argument("--severity", default=None, choices=core.SEVERITIES,
                   metavar="P0..P3",
                   help="only report findings at or above this severity")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def _select_rules(spec: Optional[str]) -> Dict[str, core.Rule]:
    rules = core.all_rules()
    if spec is None:
        return rules
    wanted = [r.strip() for r in spec.split(",") if r.strip()]
    unknown = [r for r in wanted if r not in rules]
    if unknown:
        raise SystemExit(f"graftlint: unknown rule(s): {', '.join(unknown)}"
                         f" (try --list-rules)")
    return {r: rules[r] for r in wanted}


def _resolve_root(root_arg: Optional[str], paths: Sequence[str]) -> str:
    """Directory file paths are reported relative to. The baseline keys on
    these paths, so the gate must resolve them identically from ANY cwd:
    prefer this package's repository root whenever it contains everything
    analyzed — a run from any subdirectory of the checkout (or the
    installed `graftlint` script from an arbitrary directory) then keys
    files exactly as the checked-in baseline does — and fall back to the
    cwd otherwise (other projects, tmp-dir test fixtures)."""
    if root_arg is not None:
        return os.path.abspath(root_arg)
    cwd = os.getcwd()
    abs_paths = [os.path.abspath(p) for p in paths]

    def under(base: str) -> bool:
        try:
            return all(os.path.commonpath([p, base]) == base
                       for p in abs_paths)
        except ValueError:  # different drives (windows)
            return False

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(core.__file__))))
    if under(repo_root):
        return repo_root
    return cwd


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        rules = core.all_rules()
        width = max(len(r) for r in rules)
        for rule in sorted(rules.values(),
                           key=lambda r: (r.severity, r.id)):
            print(f"{rule.id:<{width}}  {rule.severity}  {rule.doc}")
        return 0

    if args.write_baseline and (args.rules or args.severity):
        print("graftlint: refusing --write-baseline on a filtered run "
              "(--rules/--severity): it would silently drop every other "
              "rule's grandfathered entries. Rerun unfiltered.",
              file=sys.stderr)
        return 2

    rules = _select_rules(args.rules)
    modules: Dict[str, core.Module] = {}
    # Analyze with suppressions OFF and split afterwards: the audit view
    # (--no-suppressions) must never leak suppressed findings into the
    # gating set, so the exit code stays identical either way.
    try:
        findings = core.analyze_paths(
            args.paths, rules=rules,
            root=_resolve_root(args.root, args.paths),
            respect_suppressions=False, collect_sources=modules)
    except FileNotFoundError as e:
        # A missing target is a broken invocation, not a clean tree.
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    if args.severity is not None:
        cutoff = core.SEVERITIES.index(args.severity)
        findings = [f for f in findings
                    if core.SEVERITIES.index(f.severity) <= cutoff]
    suppressed = [f for f in findings
                  if f.file in modules and modules[f.file].suppressed(f)]
    gated = [f for f in findings
             if not (f.file in modules and modules[f.file].suppressed(f))]

    if args.write_baseline:
        # A path-subset run (`--write-baseline some/dir`) must not drop
        # grandfathered entries belonging to files it never analyzed —
        # the same hazard the --rules/--severity refusal above guards.
        # Keep those verbatim; entries for analyzed files are replaced
        # (so fixing findings still shrinks the file).
        kept = {key: n
                for key, n in core.load_baseline(args.baseline).items()
                if key[1] not in modules}
        path = core.write_baseline(gated, modules, args.baseline, keep=kept)
        print(f"graftlint: wrote {len(gated)} finding(s) to {path}"
              + (f" (kept {sum(kept.values())} for unanalyzed files)"
                 if kept else ""))
        return 0

    baseline = core.load_baseline(args.baseline)
    new, grandfathered = core.apply_baseline(gated, modules, baseline)

    if args.as_json:
        doc = {
            "findings": [f.to_json() for f in new],
            "baselined": ([f.to_json() for f in grandfathered]
                          if args.no_baseline else len(grandfathered)),
            "suppressed": ([f.to_json() for f in suppressed]
                           if args.no_suppressions else len(suppressed)),
            "counts": _counts(new),
            "ok": not new,
        }
        print(json.dumps(doc, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if args.no_baseline and grandfathered:
        print(f"-- {len(grandfathered)} baselined finding(s):")
        for f in grandfathered:
            print("   " + f.render())
    if args.no_suppressions and suppressed:
        print(f"-- {len(suppressed)} suppressed finding(s) (audit view; "
              "not gated):")
        for f in suppressed:
            print("   " + f.render())
    if new:
        counts = ", ".join(f"{n} {sev}" for sev, n in _counts(new).items())
        print(f"graftlint: {len(new)} finding(s) ({counts}); "
              f"{len(grandfathered)} baselined")
        return 1
    suffix = f" ({len(grandfathered)} baselined)" if grandfathered else ""
    print(f"graftlint: clean{suffix}")
    return 0


def _counts(findings) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.severity] = out.get(f.severity, 0) + 1
    return dict(sorted(out.items()))


def _cli() -> int:
    try:
        return main()
    except BrokenPipeError:
        # `graftlint ... | head` closing the pipe early is not an error.
        return 0


if __name__ == "__main__":
    sys.exit(_cli())
