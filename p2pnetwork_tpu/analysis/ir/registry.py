"""graftaudit lowering registry: every propagation variant × shape-class.

One :class:`Lowering` entry names one compiled-code path the repo ships —
``or/frontier@ws1k`` is "propagate_or through the frontier-compacted
lowering on the quasi-regular 1k Watts-Strogatz class". ``build()`` returns
``(fn, args)``; everything downstream is abstract: :func:`trace_lowering`
produces the jaxpr, the primitive census, the collective census with
estimated ICI bytes, and the canonical output signature — no device work,
no concrete execution, so the whole registry audits in CPU-only CI.

Shape-classes are deliberately SMALL (1k nodes): jaxpr structure, rule
verdicts, signature parity, and the *relative* cost ratchet are all
shape-class-stable — what drifts with a bad PR is the program, not the
problem size — and small classes keep the gate sub-minute. Two classes
cover the routing space: ``ws1k`` (quasi-regular; ``auto`` routes to
gather) and ``ba1k`` (degree-skewed with a skew table; ``auto`` routes to
skew), matching the measured break-evens in ops/segment.py.

Entries in the same ``(op, shape_class)`` parity group must agree on
``eval_shape`` signatures — the cross-lowering parity gate in
:mod:`.rules`. Representation-changing variants (the bitset flood step)
participate through a normalizing wrapper (bool in, bool out) so the gate
compares the LOGICAL op, not the carry encoding; backends with a different
contract (the sharded [S, block] layout) opt out via ``parity=False`` and
are still censused, rule-checked, and cost-ratcheted.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["Lowering", "Trace", "all_lowerings", "zoo_at", "shape_class",
           "parse_shape_class", "trace_lowering", "signature_text",
           "COLLECTIVE_PRIMS"]

#: Cross-device primitives the census tracks, with the per-occurrence ICI
#: byte model: bytes moved ≈ operand_bytes × factor(S) on an S-way ring —
#: ppermute moves each operand once; psum (ring all-reduce) moves
#: 2·(S-1)/S ≈ 2 copies; all_gather moves (S-1) shard-sized pieces. The
#: model itself lives in parallel/commviz.ring_model_bytes — one model
#: feeding both this census ratchet and commviz's comm estimates — and
#: the census ALSO counts Pallas ring-DMA kernels (ops/pallas_ring.py
#: ``make_async_remote_copy`` halo hops, recognized by kernel name) under
#: the ``commviz.RING_DMA_KEY`` pseudo-collective: a Pallas-comm lowering
#: would otherwise read as zero ICI bytes and silently pass the budget
#: ratchet.
COLLECTIVE_PRIMS = ("ppermute", "psum", "all_gather", "all_to_all",
                    "reduce_scatter", "pmax", "pmin")


@dataclasses.dataclass(frozen=True)
class Lowering:
    """One auditable lowering: a name, its parity group, and a builder.

    ``build()`` -> ``(fn, args)`` with ``fn(*args)`` traceable (``fn`` may
    already be jitted — pjit traces and lowers like any function).
    ``slot_budget`` is the frontier gather bound in SLOTS (``k · span``)
    for entries riding the compaction path; None disables the slot rule.
    ``needs_devices`` gates entries that only trace on a multi-device
    mesh (the sharded ppermute path needs the 8-way virtual CPU mesh)
    and doubles as the mesh width the ICI byte model prices collectives
    at — the entry builds its own mesh, so the width is static registry
    knowledge.
    """

    name: str
    op: str
    variant: str
    shape_class: str
    build: Callable[[], Tuple[Callable, tuple]]
    parity: bool = True
    slot_budget: Optional[int] = None
    needs_devices: int = 1
    doc: str = ""


@dataclasses.dataclass
class Trace:
    """Abstract-trace artifacts of one lowering (device-free)."""

    entry: Lowering
    jaxpr: Optional[object] = None        # ClosedJaxpr
    out_sig: Optional[str] = None         # canonical eval_shape signature
    prims: Dict[str, int] = dataclasses.field(default_factory=dict)
    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    ici_bytes_est: int = 0
    error: Optional[str] = None           # trace failure (becomes a finding)


# ------------------------------------------------------------ shape-classes

# Bounded by construction: keys come from parse_shape_class (the two
# canonical audit classes plus the handful of scaled fit points the
# capacity planner traces), each a one-time host graph build.
_GRAPH_CACHE: Dict[str, object] = {}  # graftlint: ignore[unbounded-cache] -- keyed on the finite shape-class vocabulary (2 audit classes + capacity fit points), not on user input


def parse_shape_class(name: str) -> Tuple[str, int]:
    """``(family, n_nodes)`` of a shape-class name: ``ws1k`` -> ("ws",
    1024), ``ba256`` -> ("ba", 256). The canonical audit classes are
    ``ws1k``/``ba1k``; scaled siblings (``ws256``, ``ws512``, ...) exist
    so the capacity planner can trace one lowering at several shape
    points and fit its memory model — same generators, same seed, only
    the node count moves."""
    import re

    m = re.fullmatch(r"(ws|ba)(\d+)(k?)", name)
    if not m:
        raise ValueError(f"unknown shape-class {name!r}")
    return m.group(1), int(m.group(2)) * (1024 if m.group(3) else 1)


def shape_class(name: str):
    """The canonical graph of one shape-class (host-built, cached)."""
    g = _GRAPH_CACHE.get(name)
    if g is None:
        from p2pnetwork_tpu.sim import graph as G

        family, n = parse_shape_class(name)
        if family == "ws":
            # Quasi-regular small-world: `auto` routes to gather; carries
            # every single-chip representation the zoo lowers through.
            g = G.watts_strogatz(n, 6, 0.2, seed=0, blocked=True,
                                 skew_table=True, source_csr=True)
        else:
            # Degree-skewed scale-free: the skew table's home class
            # (`auto` routes to skew once the gather waste bound trips).
            g = G.barabasi_albert(n, 3, seed=0, skew_table=True,
                                  source_csr=True)
        _GRAPH_CACHE[name] = g
    return g


def _signal(g, dtype):
    n = g.n_nodes_padded
    if dtype is bool:
        return jnp.zeros(n, dtype=bool)
    return jnp.zeros(n, dtype=jnp.float32)


def _frontier_slots(g) -> Optional[int]:
    """The compaction buffer's slot bound (ops/frontier.py owns the
    arithmetic), or None when the auto budget disables the sparse path
    on this class."""
    from p2pnetwork_tpu.ops import frontier as FR

    return FR.budget_slots(g) or None


# ------------------------------------------------------------ entry builders


def _kernel_entry(op: str, variant: str, cls: str, *, dtype=bool,
                  parity: bool = True, doc: str = "") -> Lowering:
    """A propagate_* kernel × method entry (ops/segment.py dispatch)."""

    def build():
        from p2pnetwork_tpu.ops import segment as S

        g = shape_class(cls)
        kernel = {"or": S.propagate_or, "sum": S.propagate_sum,
                  "max": S.propagate_max, "minplus": S.propagate_min_plus}[op]
        sig = _signal(g, dtype)
        return functools.partial(kernel, g, method=variant), (sig,)

    slot = None
    if variant == "frontier":
        slot = _frontier_slots(shape_class(cls))
    return Lowering(name=f"{op}/{variant}@{cls}", op=op, variant=variant,
                    shape_class=cls, build=build, parity=parity,
                    slot_budget=slot, doc=doc)


def _flood_step_entry(variant: str, cls: str) -> Lowering:
    """The flood protocol step — dense bool state vs the bit-packed
    carry (ops/bitset.py), normalized to bool-in/bool-out so the parity
    gate compares the logical round, not the carry encoding."""

    def build():
        from p2pnetwork_tpu.models.flood import (Flood, FloodBitState,
                                                 FloodState)
        from p2pnetwork_tpu.ops import bitset

        g = shape_class(cls)
        proto = Flood(source=0, bitset=(variant == "bitset"))
        key = jax.random.key(0)

        def step(seen, frontier):
            if variant == "bitset":
                st = FloodBitState(seen=bitset.pack_bits(seen),
                                   frontier=bitset.pack_bits(frontier))
                st, stats = proto.step(g, st, key)
                n = g.n_nodes_padded
                return (bitset.unpack_bits(st.seen, n),
                        bitset.unpack_bits(st.frontier, n), stats)
            st, stats = proto.step(g, FloodState(seen=seen,
                                                 frontier=frontier), key)
            return st.seen, st.frontier, stats

        sig = _signal(g, bool)
        return step, (sig, sig)

    return Lowering(name=f"floodstep/{variant}@{cls}", op="floodstep",
                    variant=variant, shape_class=cls, build=build)


def _lanes_kernel_entry(variant: str, cls: str) -> Lowering:
    """A lane-packed ``propagate_or_lanes`` × method entry (the batched
    message plane's round kernel, ops/segment.py): u32[1, N] in/out —
    one word = 32 concurrent messages; the vmap-over-words outer
    dimension is shape-polymorphic, so one word audits the program every
    width runs. The frontier variant's slot budget is the LANE bound
    (``budget_slots_lanes``): the compacted gather is shared, the
    scatter moves a 32-wide bit-plane row per slot."""

    def build():
        from p2pnetwork_tpu.ops import segment as S

        g = shape_class(cls)
        lanes = jnp.zeros((1, g.n_nodes_padded), dtype=jnp.uint32)
        return functools.partial(S.propagate_or_lanes, g,
                                 method=variant), (lanes,)

    slot = None
    if variant == "frontier":
        from p2pnetwork_tpu.ops import frontier as FR

        slot = FR.budget_slots_lanes(shape_class(cls), n_words=1) or None
    return Lowering(name=f"or_lanes/{variant}@{cls}", op="or_lanes",
                    variant=variant, shape_class=cls, build=build,
                    slot_budget=slot)


def _query_lanes_entry(op: str, variant: str, cls: str) -> Lowering:
    """A non-boolean query-lane kernel × method entry (ops/lanes.py):
    ``f32[N_pad, 8]`` node-major lane matrices — the K axis is
    shape-polymorphic (every op is lane-elementwise or a per-lane
    reduction), so 8 lanes audit the program every width runs. The
    gather/segment pair per shape-class is a PARITY group, like the
    scalar kernels'."""

    def build():
        from p2pnetwork_tpu.ops import lanes as L

        g = shape_class(cls)
        kernel = {"minplus_lanes": L.propagate_min_plus_lanes,
                  "sum_lanes": L.propagate_sum_lanes}[op]
        mat = jnp.zeros((g.n_nodes_padded, 8), dtype=jnp.float32)
        return functools.partial(kernel, g, method=variant), (mat,)

    return Lowering(name=f"{op}/{variant}@{cls}", op=op, variant=variant,
                    shape_class=cls, build=build)


def _dht_hop_entry(cls: str) -> Lowering:
    """The batched DHT hop kernel (ops/lanes.dht_hop_lanes): one
    neighbor-row gather + metric argmin serving K greedy lookups —
    i32[16] cursors/keys (K shape-polymorphic like the other lane
    kernels)."""

    def build():
        from p2pnetwork_tpu.ops import lanes as L

        g = shape_class(cls)
        cur = jnp.zeros(16, dtype=jnp.int32)
        keys = jnp.arange(16, dtype=jnp.int32)
        return functools.partial(L.dht_hop_lanes, g,
                                 metric="ring"), (cur, keys)

    return Lowering(name=f"dht_hop/ring@{cls}", op="dht_hop",
                    variant="ring", shape_class=cls, build=build,
                    parity=False)


def _engine_query_entry(cls: str) -> Lowering:
    """The batched query loop (engine._query_loop): K=8 min-plus route
    lookups with per-lane freeze and the packed per-lane answer
    summary — the queries bench column's measured shape, censused and
    cost-ratcheted like the batched flood loop."""

    def build():
        import numpy as np

        from p2pnetwork_tpu.models.querybatch import MinPlusQueries
        from p2pnetwork_tpu.sim import engine

        g = shape_class(cls)
        proto = MinPlusQueries(method="auto")
        qb = proto.init(g, np.arange(8, dtype=np.int32) * 11 % 900,
                        np.arange(8, dtype=np.int32) * 37 % 900)

        def run(graph, b, key):
            return engine._query_loop_keeping(graph, proto, b, key,
                                              max_rounds=64)

        return run, (g, qb, jax.random.key(0))

    return Lowering(name=f"done/queries-engine@{cls}", op="done",
                    variant="queries-engine", shape_class=cls,
                    build=build, parity=False)


def _engine_batch_cov_entry(cls: str) -> Lowering:
    """The batched run-to-coverage loop (engine._batch_loop): B=32
    lane-packed floods, per-lane completion detection, packed per-lane
    summary — the batched bench column's measured shape, censused and
    cost-ratcheted like the single-message loop."""

    def build():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.sim import engine

        g = shape_class(cls)
        proto = BatchFlood(method="auto")
        batch = proto.init(g, np.arange(32, dtype=np.int32) * 7 % 1000)

        def cov(graph, b, key):
            return engine._batch_loop_keeping(graph, proto, b, key,
                                              max_rounds=64)

        return cov, (g, batch, jax.random.key(0))

    return Lowering(name=f"cov/batchflood-engine@{cls}", op="cov",
                    variant="batchflood-engine", shape_class=cls,
                    build=build, parity=False)


def _engine_cov_rec_entry(cls: str) -> Lowering:
    """The run-to-coverage resume loop with the graftscope flight
    recorder in the carry (engine._coverage_loop_rec): the ring-row
    write must stay one dynamic_update_slice per round — censused and
    cost-ratcheted so recorder overhead cannot silently grow."""

    def build():
        import jax.numpy as jnp

        from p2pnetwork_tpu.models.flood import Flood, FloodState
        from p2pnetwork_tpu.sim import engine, flightrec

        g = shape_class(cls)
        proto = Flood(source=0)
        seed = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        seed = seed & g.node_mask
        state = FloodState(seen=seed | jnp.zeros_like(seed),
                           frontier=jnp.zeros_like(seed).at[1].set(True))
        ring = flightrec.FlightRecorder(capacity=64).init()

        def cov(graph, st, key, rg):
            return engine._coverage_loop_rec_keeping(
                graph, proto, st, key, rg, coverage_target=0.99,
                max_rounds=64)

        return cov, (g, state, jax.random.key(0), ring)

    return Lowering(name=f"cov/flood-engine-rec@{cls}", op="cov",
                    variant="flood-engine-rec", shape_class=cls,
                    build=build, parity=False)


def _engine_batch_cov_rec_entry(cls: str) -> Lowering:
    """The batched run-to-coverage loop with the flight recorder
    (engine._batch_loop_rec) — the recorder-enabled twin of
    ``cov/batchflood-engine``."""

    def build():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.sim import engine, flightrec

        g = shape_class(cls)
        proto = BatchFlood(method="auto")
        batch = proto.init(g, np.arange(32, dtype=np.int32) * 7 % 1000)
        ring = flightrec.FlightRecorder(capacity=64).init()

        def cov(graph, b, key, rg):
            return engine._batch_loop_rec_keeping(graph, proto, b, key, rg,
                                                  max_rounds=64)

        return cov, (g, batch, jax.random.key(0), ring)

    return Lowering(name=f"cov/batchflood-engine-rec@{cls}", op="cov",
                    variant="batchflood-engine-rec", shape_class=cls,
                    build=build, parity=False)


def _engine_cov_entry(cls: str) -> Lowering:
    """The single-chip run-to-coverage loop (engine._coverage_with_init):
    init + early-exit while_loop + packed summary in one program — the
    1M/10M bench stages' measured shape, censused and cost-ratcheted."""

    def build():
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = shape_class(cls)
        proto = Flood(source=0)

        def cov(graph, key):
            return engine._coverage_with_init(
                graph, proto, key, coverage_target=0.99, max_rounds=64)

        return cov, (g, jax.random.key(0))

    return Lowering(name=f"cov/flood-engine@{cls}", op="cov",
                    variant="flood-engine", shape_class=cls, build=build,
                    parity=False)


def _ring_step_entry(variant: str, cls: str) -> Lowering:
    """One ring OR pass per halo-exchange backend (sharded.propagate's
    compiled program, ``comm=ppermute`` vs ``comm=pallas``) — a PARITY
    group: both backends must agree on the abstract signature, and the
    census prices the ppermute hops and the Pallas ring DMAs through the
    same byte model, so the ratchet pins the two backends' ICI budgets
    against each other."""

    def build():
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.parallel import sharded as SH

        g = shape_class(cls)
        mesh = M.ring_mesh(8)
        sg = SH.shard_graph(g, mesh)
        fn = SH._propagate_fn(mesh, SH.DEFAULT_AXIS, sg.n_shards, sg.block,
                              "or", sg.diag_pieces, sg.mxu_block, variant)
        args = (sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *SH._dyn_or_empty(sg), *SH._mxu_or_empty(sg),
                SH._diag_masks_or_empty(sg), sg.node_mask,
                SH._flood_seed(sg, 0))
        return fn, args

    return Lowering(name=f"ringstep/{variant}@{cls}", op="ringstep",
                    variant=variant, shape_class=cls, build=build,
                    needs_devices=8)


def _sharded_or_lanes_entry(cls: str) -> Lowering:
    """The lane-word halo ring pass (sharded.propagate_or_lanes): one
    ``u32[W, block]`` hop per ring step carries 32·W in-flight messages'
    boundary state. Layout-specific ``[S, W, block]`` signature —
    censused and cost-ratcheted, parity=False like the other sharded
    programs."""

    def build():
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.parallel import sharded as SH

        g = shape_class(cls)
        mesh = M.ring_mesh(8)
        sg = SH.shard_graph(g, mesh)
        fn = SH._or_lanes_fn(mesh, SH.DEFAULT_AXIS, sg.n_shards, sg.block)
        lanes = SH.shard_lanes(
            sg, jnp.zeros((1, g.n_nodes_padded), jnp.uint32))
        args = (sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *SH._dyn_or_empty(sg), sg.node_mask, lanes)
        return fn, args

    return Lowering(name=f"or_lanes/sharded-ring@{cls}", op="or_lanes",
                    variant="sharded-ring", shape_class=cls, build=build,
                    parity=False, needs_devices=8)


def _sharded_batch_cov_entry(cls: str) -> Lowering:
    """The sharded batched-flood loop (sharded.run_batch_until_coverage):
    the lane-word halo inside the run-to-coverage while_loop — the
    multi-chip batched plane's measured shape."""

    def build():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.parallel import sharded as SH

        g = shape_class(cls)
        mesh = M.ring_mesh(8)
        sg = SH.shard_graph(g, mesh)
        batch = BatchFlood().init(g, np.arange(32, dtype=np.int32) * 7 % 1000)
        fn = SH._batch_cov_fn(mesh, SH.DEFAULT_AXIS, sg.n_shards, sg.block,
                              64)
        args = (sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *SH._dyn_or_empty(sg), sg.node_mask, sg.out_degree,
                *SH._shard_batch_args(sg, batch))
        return fn, args

    return Lowering(name=f"cov/batchflood-ring@{cls}", op="cov",
                    variant="batchflood-ring", shape_class=cls, build=build,
                    parity=False, needs_devices=8)


def _sharded_cov_entry(cls: str) -> Lowering:
    """The multi-chip ppermute coverage loop (parallel/sharded.py): the
    ring pass whose collective census — ppermute/psum occurrences and
    estimated ICI bytes — feeds the commviz comm budgets."""

    def build():
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.parallel import sharded as SH

        g = shape_class(cls)
        mesh = M.ring_mesh(8)
        sg = SH.shard_graph(g, mesh)
        seen0, frontier0 = SH.init_state(sg, Flood(source=0), None)
        fn = SH._flood_cov_fn(mesh, SH.DEFAULT_AXIS, sg.n_shards, sg.block,
                              64, sg.diag_pieces, sg.mxu_block)
        args = (jnp.float32(0.99), sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *SH._dyn_or_empty(sg), *SH._mxu_or_empty(sg),
                SH._diag_masks_or_empty(sg), sg.node_mask, sg.out_degree,
                seen0, frontier0)
        return fn, args

    return Lowering(name=f"cov/flood-ppermute@{cls}", op="cov",
                    variant="flood-ppermute", shape_class=cls, build=build,
                    parity=False, needs_devices=8)


def zoo_at(ws: str = "ws1k", ba: str = "ba1k") -> List[Lowering]:
    """The registry's entry set built against arbitrary shape-classes —
    ``all_lowerings()`` is ``zoo_at()`` at the canonical audit classes;
    the capacity planner calls it at scaled siblings (``ws256``, ...) to
    trace the same programs at several shape points."""
    entries: List[Lowering] = []
    for v in ("segment", "gather", "blocked", "skew", "frontier"):
        entries.append(_kernel_entry("or", v, ws, dtype=bool))
    for v in ("segment", "gather", "blocked", "skew"):
        entries.append(_kernel_entry("sum", v, ws, dtype=float))
    for v in ("segment", "gather", "skew", "frontier"):
        entries.append(_kernel_entry("max", v, ws, dtype=float))
    for v in ("segment", "gather", "skew", "frontier"):
        entries.append(_kernel_entry("minplus", v, ws, dtype=float))
    entries.append(_flood_step_entry("dense", ws))
    entries.append(_flood_step_entry("bitset", ws))
    # The lane-packed batched kernels (32 messages per word) and the
    # batched engine loop — the message plane's compiled surface.
    for v in ("segment", "gather", "frontier"):
        entries.append(_lanes_kernel_entry(v, ws))
    # The non-boolean query-lane kernels (f32/i32 lane carriers,
    # ops/lanes.py) and the batched query engine loop — PR 14's
    # compiled surface. The gather/segment pairs are parity groups on
    # ws1k; ba1k registers the auto-dispatch answer there (the gather
    # waste bound trips, no skew lane form exists -> segment).
    for v in ("gather", "segment"):
        entries.append(_query_lanes_entry("minplus_lanes", v, ws))
        entries.append(_query_lanes_entry("sum_lanes", v, ws))
    entries.append(_dht_hop_entry(ws))
    entries.append(_engine_query_entry(ws))
    entries.append(_engine_cov_entry(ws))
    entries.append(_engine_batch_cov_entry(ws))
    # The graftscope flight-recorder twins of the engine loops: same
    # programs plus one ring-row write per round, censused so recorder
    # overhead stays visible in the cost ratchet.
    entries.append(_engine_cov_rec_entry(ws))
    entries.append(_engine_batch_cov_rec_entry(ws))
    entries.append(_sharded_cov_entry(ws))
    # The halo-exchange seam: ppermute vs pallas ring DMAs as
    # signature-parity peers, plus the lane-word halo programs the
    # batched plane rides multi-chip.
    entries.append(_ring_step_entry("ppermute", ws))
    entries.append(_ring_step_entry("pallas", ws))
    entries.append(_sharded_or_lanes_entry(ws))
    entries.append(_sharded_batch_cov_entry(ws))
    # The degree-skewed class: the three lowerings whose crossover the
    # routing actually arbitrates there (segment vs skew vs frontier) —
    # and the batched kernels' own arbitrated pair (lanes-auto routes to
    # segment on skewed tables; frontier shares the compaction budget).
    for v in ("segment", "skew", "frontier"):
        entries.append(_kernel_entry("or", v, ba, dtype=bool))
    for v in ("segment", "frontier"):
        entries.append(_lanes_kernel_entry(v, ba))
    for op in ("minplus_lanes", "sum_lanes"):
        entries.append(_query_lanes_entry(op, "segment", ba))
    return entries


def all_lowerings() -> List[Lowering]:
    """The full registry, parity-grouped by ``(op, shape_class)``.

    Variant lists mirror the dispatch tables in ops/segment.py (max/min
    ride no MXU lowering; skew needs the two-level table the class
    carries). The pallas/hybrid MXU kernels are chip-only programs — they
    do not lower on the CPU backend — and are audited at the source level
    by graftlint instead.
    """
    return zoo_at("ws1k", "ba1k")


# ----------------------------------------------------------------- tracing


def _walk_jaxpr(jaxpr, visit) -> None:
    """Depth-first over every eqn of ``jaxpr`` and every sub-jaxpr in its
    params (cond branches, while/scan bodies, pjit/shard_map callees)."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for v in eqn.params.values():
            for x in (v if isinstance(v, (tuple, list)) else (v,)):
                inner = getattr(x, "jaxpr", None)
                if hasattr(x, "eqns"):
                    _walk_jaxpr(x, visit)
                elif inner is not None and hasattr(inner, "eqns"):
                    _walk_jaxpr(inner, visit)


def iter_eqns(closed_jaxpr):
    """Every eqn of a ClosedJaxpr, sub-jaxprs included (list, docs order)."""
    out = []
    _walk_jaxpr(closed_jaxpr.jaxpr, out.append)
    return out


def signature_text(shapes) -> str:
    """Canonical text of an ``eval_shape`` result tree: dtype[shape] per
    leaf, joined in tree order — the string the parity gate compares."""
    leaves = jax.tree_util.tree_leaves(shapes)
    parts = [f"{jnp.dtype(l.dtype).name}[{','.join(map(str, l.shape))}]"
             for l in leaves]
    return "; ".join(parts)


def _collective_bytes(eqn, prim: str, axis_size: int) -> int:
    """The ring-model byte estimate of one collective eqn. ``axis_size``
    is the entry's mesh width — static registry knowledge (the entry
    builds its own mesh), not a runtime axis-env lookup, which is not
    available when walking a finished jaxpr. The model itself is
    commviz.ring_model_bytes (shared with the runtime comm estimates)."""
    from p2pnetwork_tpu.parallel import commviz

    nbytes = sum(int(getattr(v.aval, "size", 0))
                 * jnp.dtype(v.aval.dtype).itemsize
                 for v in eqn.invars if hasattr(v, "aval"))
    return commviz.ring_model_bytes(prim, nbytes, axis_size)


def trace_lowering(entry: Lowering) -> Trace:
    """Abstractly trace one lowering: jaxpr, output signature, primitive
    and collective censuses. Never raises — an untraceable lowering is a
    P1 finding (rules.py), not a dead audit."""
    trace = Trace(entry=entry)
    try:
        fn, args = entry.build()
        closed = jax.make_jaxpr(fn)(*args)
        trace.jaxpr = closed
        # The jaxpr's out_avals ARE the eval_shape result (flattened) —
        # reading them here instead of calling jax.eval_shape avoids a
        # full second abstract trace of every registry entry.
        trace.out_sig = signature_text(closed.out_avals)
    except Exception as e:  # noqa: BLE001 — any failure is the finding
        trace.error = f"{type(e).__name__}: {e}"
        return trace
    from p2pnetwork_tpu.parallel import commviz

    for eqn in iter_eqns(closed):
        prim = eqn.primitive.name
        trace.prims[prim] = trace.prims.get(prim, 0) + 1
        if prim in COLLECTIVE_PRIMS:
            trace.collectives[prim] = trace.collectives.get(prim, 0) + 1
            trace.ici_bytes_est += _collective_bytes(
                eqn, prim, entry.needs_devices)
        else:
            # Pallas ring-DMA halo hops (ops/pallas_ring.py) — censused
            # as a pseudo-collective so a Pallas-comm lowering's ICI
            # traffic is budgeted like its ppermute twin's.
            payload = commviz.ring_dma_payload_bytes(eqn)
            if payload:
                key = commviz.RING_DMA_KEY
                trace.collectives[key] = trace.collectives.get(key, 0) + 1
                trace.ici_bytes_est += commviz.ring_model_bytes(
                    key, payload, entry.needs_devices)
    return trace
