"""graftaudit jaxpr rules: what a lowering must never compile to.

Rules run over :class:`~p2pnetwork_tpu.analysis.ir.registry.Trace`
artifacts — pure jaxpr inspection, no device, no execution — and emit the
same :class:`~p2pnetwork_tpu.analysis.core.Finding` records graftlint
uses, with the LOWERING NAME in the file slot (``or/frontier@ws1k:0``)
so baselines fingerprint on (rule, lowering) exactly like source findings
fingerprint on (rule, file, line text).

========================  =====  ==============================================
rule                      sev    fires on
========================  =====  ==============================================
``ir-trace-error``        P1     a registry lowering that no longer traces —
                                 a dead entry gates nothing
``ir-host-callback``      P0     host callback primitives (pure_callback /
                                 io_callback / debug_callback ...) inside a
                                 lowering — a device->host sync EVERY round,
                                 invisible to timing until it is the bench
``ir-f64-widen``          P1     convert_element_type to f64, or any f64
                                 value flowing through the jaxpr — doubled
                                 bandwidth on chip, silent f32 truncation
                                 under default x64-off
``ir-gather-slot-budget`` P1     a frontier-compacted lowering none of whose
                                 branches keeps gather/scatter traffic within
                                 the ``k·span`` slot budget — the compaction
                                 is broken and every round pays dense cost
``ir-sig-parity``         P0     lowerings of one (op, shape-class) group
                                 disagreeing on eval_shape signatures —
                                 variants are no longer interchangeable
========================  =====  ==============================================
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Sequence

import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.analysis.core import SEVERITIES, Finding
from p2pnetwork_tpu.analysis.ir.registry import Trace, iter_eqns

__all__ = ["IRRule", "all_ir_rules", "run_ir_rules", "parity_findings"]

#: Primitive names that call back into the host. Any of these inside a
#: lowering serializes every execution on a device->host round trip.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "xla_python_cpu_callback",
})


@dataclasses.dataclass(frozen=True)
class IRRule:
    """One jaxpr check: ``run(trace)`` yields messages; id/severity are
    stamped into Findings here (mirrors core.Rule for Module rules)."""

    id: str
    severity: str
    doc: str
    run: Callable[[Trace], Iterable[str]]


_IR_RULES: Dict[str, IRRule] = {}  # graftlint: ignore[unbounded-cache] -- rule registry populated once at import by @_register, fixed vocabulary


def _register(id: str, severity: str, doc: str):
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}")

    def deco(fn):
        _IR_RULES[id] = IRRule(id=id, severity=severity, doc=doc, run=fn)
        return fn
    return deco


def all_ir_rules() -> Dict[str, IRRule]:
    return dict(_IR_RULES)


def _finding(rule: IRRule, trace: Trace, message: str) -> Finding:
    return Finding(severity=rule.severity, file=trace.entry.name, line=0,
                   col=0, rule=rule.id, message=message)


def run_ir_rules(traces: Sequence[Trace],
                 rules: Dict[str, IRRule] = None) -> List[Finding]:
    """Every rule over every trace, sorted worst-first like graftlint."""
    rules = rules if rules is not None else all_ir_rules()
    out: List[Finding] = []
    for trace in traces:
        for rule in rules.values():
            out.extend(_finding(rule, trace, msg)
                       for msg in rule.run(trace))
    return sorted(out)


# ----------------------------------------------------------------- rules


@_register(
    "ir-trace-error", "P1",
    "A registry lowering failed to trace — the audit can no longer see "
    "this code path, so the gate is silently off for it.")
def rule_trace_error(trace: Trace) -> Iterable[str]:
    if trace.error is not None:
        yield (f"lowering failed to trace: {trace.error} — fix the entry "
               "or the code path it names; an untraceable lowering is "
               "ungated")


@_register(
    "ir-host-callback", "P0",
    "Host callback primitive compiled into a lowering: every execution "
    "blocks on a device->host round trip.")
def rule_host_callback(trace: Trace) -> Iterable[str]:
    for prim, n in sorted(trace.prims.items()):
        if prim in CALLBACK_PRIMS:
            yield (f"{n} `{prim}` op(s) compiled into this lowering — a "
                   "host sync per execution; compute device-side or move "
                   "the callback outside the hot program")


@_register(
    "ir-f64-widen", "P1",
    "float64 values in a lowered jaxpr: doubled HBM/ICI bandwidth under "
    "x64-on, silent f32 truncation under the default x64-off — either "
    "way a drift from the sim's f32 discipline.")
def rule_f64_widen(trace: Trace) -> Iterable[str]:
    if trace.jaxpr is None:
        return
    f64 = jnp.dtype(np.float64)  # graftlint: ignore[f64-literal] -- the rule must name the dtype it hunts; nothing computes in f64 here
    widens = 0
    carriers: Dict[str, int] = {}
    for eqn in iter_eqns(trace.jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is None or getattr(aval, "dtype", None) != f64:
                continue
            if eqn.primitive.name == "convert_element_type":
                widens += 1
            else:
                name = eqn.primitive.name
                carriers[name] = carriers.get(name, 0) + 1
    if widens:
        yield (f"{widens} convert_element_type op(s) widening to float64 "
               "— pick an explicit f32 (or isolate the precision need) "
               "instead of letting x64 flags decide")
    if carriers:
        ops = ", ".join(f"{p}×{n}" for p, n in sorted(carriers.items()))
        yield (f"float64 values flow through this lowering ({ops}) — "
               "f64 doubles bandwidth on every byte it touches")


@_register(
    "ir-gather-slot-budget", "P1",
    "A frontier-compacted lowering whose gather/scatter traffic exceeds "
    "the k·span slot budget on EVERY branch: the sparse path no longer "
    "bounds its work by the frontier.")
def rule_gather_slot_budget(trace: Trace) -> Iterable[str]:
    budget = trace.entry.slot_budget
    if budget is None or trace.jaxpr is None:
        return
    # The compiled program carries BOTH rounds (lax.cond: sparse within
    # budget, dense fallback past it). The invariant is existential: some
    # branch of each cond must keep its gather/scatter slots within the
    # budget — if none does, the compaction itself is broken and every
    # round pays dense-gather cost. Branch order in the jaxpr is an
    # implementation detail, so the rule checks all of them.
    conds = [e for e in iter_eqns(trace.jaxpr)
             if e.primitive.name == "cond" and "branches" in e.params]
    if not conds:
        yield ("no lax.cond sparse/dense dispatch found in a lowering "
               "with a frontier slot budget — the compaction (and its "
               "dense fallback) has been compiled out")
        return
    for eqn in conds:
        worst_per_branch = []
        for branch in eqn.params["branches"]:
            slots = 0
            for sub in iter_eqns(branch):
                prim = sub.primitive.name
                if prim == "gather":
                    slots = max(slots, int(sub.outvars[0].aval.size))  # graftlint: ignore[host-sync-in-loop] -- aval.size is static trace-time metadata, not a device value
                elif prim.startswith("scatter"):
                    # operands are (target, indices, updates); the traffic
                    # the budget bounds is the updates being scattered.
                    slots = max(slots, int(sub.invars[-1].aval.size))  # graftlint: ignore[host-sync-in-loop] -- static aval metadata again
            worst_per_branch.append(slots)
        if worst_per_branch and min(worst_per_branch) > budget:
            yield (f"every branch of the sparse/dense cond moves more "
                   f"slots than the frontier budget (min branch "
                   f"{min(worst_per_branch)} > k·span {budget}) — the "
                   "compaction no longer bounds work by the frontier")


# ------------------------------------------------------------ parity gate


def parity_findings(traces: Sequence[Trace]) -> List[Finding]:
    """The cross-lowering abstract-signature gate: every traced lowering
    of one ``(op, shape_class)`` parity group must produce the identical
    ``eval_shape`` signature — otherwise the variants stopped being
    interchangeable and every "bit-exact vs dense" claim is void. The
    majority signature is treated as intended; minority entries get the
    P0 finding (so one broken variant yields one finding, not N-1)."""
    groups: Dict[tuple, List[Trace]] = {}
    for t in traces:
        if t.entry.parity and t.out_sig is not None:
            groups.setdefault((t.entry.op, t.entry.shape_class),
                              []).append(t)
    out: List[Finding] = []
    for (op, cls), members in sorted(groups.items()):
        sigs: Dict[str, List[Trace]] = {}
        for t in members:
            sigs.setdefault(t.out_sig, []).append(t)
        if len(sigs) <= 1:
            continue
        majority = max(sigs.values(), key=len)[0].out_sig
        for sig, ts in sorted(sigs.items()):
            if sig == majority:
                continue
            for t in ts:
                out.append(Finding(
                    severity="P0", file=t.entry.name, line=0, col=0,
                    rule="ir-sig-parity",
                    message=(f"abstract signature diverges from the other "
                             f"`{op}@{cls}` lowerings: {sig} != {majority} "
                             "— variants of one op must be drop-in "
                             "interchangeable")))
    return sorted(out)
