"""graftmem: static HBM liveness audit + memory ratchet for the zoo.

``Compiled.memory_analysis()`` prices a program's device footprint
(argument / output / temp / alias-credited bytes) without executing it —
deterministic for a fixed (program, backend, jaxlib), exactly like the
cost ratchet's ``cost_analysis()``. This module pins those numbers per
(lowering, shape-class) into a checked-in ``membudgets.json`` with the
budgets.json tolerance-ratchet semantics (``graftaudit
--write-membudgets`` to bless), and CROSS-CHECKS each compiled record
against an analytic jaxpr buffer-liveness walk: last-use liveness over
every eqn, recursive into cond branches, while/scan bodies and
pjit/shard_map callees like the primitive census, with donation aliases
credited — the donation audit's ``input_output_alias`` pairs are the
ground truth for which argument buffers XLA reuses.

Three rules ride the record:

========================  =====  ==========================================
rule                      sev    fires on
========================  =====  ==========================================
``ir-mem-regression``     P1     compiled peak bytes drifted past the
                                 blessed tolerance (shrink past it is P2 —
                                 bless the win so the ratchet holds)
``ir-mem-unbudgeted``     P1     a lowering with no blessed memory budget
``ir-mem-model-drift``    P2     the analytic walk and the compiled
                                 record disagree by more than
                                 ``MODEL_TOLERANCE`` — the planner's
                                 closed-form extrapolations (capacity.py)
                                 can no longer be trusted for this entry
========================  =====  ==========================================

Degrade path: a backend whose ``Compiled`` objects lack
``memory_analysis()`` (or return nothing) cannot crash the audit — the
affected entries land on a skip-list (reported loudly, exactly like the
<8-device device skip-list) and ``--write-membudgets`` refuses to bless
a degraded run.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.analysis.core import Finding
from p2pnetwork_tpu.analysis.ir.donation import _alias_section
from p2pnetwork_tpu.analysis.ir.registry import Trace, parse_shape_class

__all__ = ["collect_memory", "analytic_memory", "load_membudgets",
           "write_membudgets", "check_membudgets",
           "default_membudgets_path", "DEFAULT_TOLERANCE",
           "MODEL_TOLERANCE", "MEM_UNAVAILABLE"]

SCHEMA = "graftaudit-membudgets-v1"
#: Ratchet tolerance on compiled peak bytes (same semantics as the cost
#: ratchet's: growth AND shrink past it fail until blessed).
DEFAULT_TOLERANCE = 0.20
#: Allowed analytic-vs-compiled disagreement on peak bytes. The analytic
#: walk does not model XLA fusion (it counts every jaxpr intermediate at
#: its last-use liveness), so it systematically overestimates temp; peak
#: is argument-dominated at the audit shapes, which keeps the honest
#: bound this tight.
MODEL_TOLERANCE = 0.20
#: Record marker for entries the backend could not price (no
#: ``memory_analysis`` support) — the degrade skip-list, not a failure.
MEM_UNAVAILABLE = "memory_analysis unavailable"

#: ``{output_path}: (param_index, ...)`` pairs of the compiled ENTRY
#: line's ``input_output_alias`` section — the capture group is the
#: donated PARAMETER index, which maps onto the jaxpr invar the analytic
#: walk credits.
_ALIAS_PARAM = re.compile(r"\{[\d,\s]*\}:\s*\((\d+)")


# ------------------------------------------------------- analytic walk


def _aval_bytes(aval) -> int:
    """Nominal buffer bytes of one abstract value (0 for non-arrays,
    e.g. abstract tokens or key arrays without a dtype)."""
    dtype = getattr(aval, "dtype", None)
    size = getattr(aval, "size", None)
    if dtype is None or size is None:
        return 0
    try:
        return int(size) * jnp.dtype(dtype).itemsize
    except TypeError:
        return 0


def _sub_jaxprs(eqn):
    """Every sub-jaxpr in one eqn's params (cond branches, while/scan
    bodies, pjit/shard_map callees) — the same descent the primitive
    census walks."""
    for v in eqn.params.values():
        for x in (v if isinstance(v, (tuple, list)) else (v,)):
            if hasattr(x, "eqns"):
                yield x
            else:
                inner = getattr(x, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield inner


def _liveness_peak(jaxpr, outvars_credit: frozenset) -> int:
    """Peak live intermediate bytes of one (open) jaxpr under last-use
    liveness. Vars in ``outvars_credit`` (the program's own outputs)
    are excluded — they are output buffers, not temps. Control-flow
    eqns contribute their bodies' peaks as a transient at their program
    point (branches never run concurrently, so cond takes the max)."""
    eqns = list(getattr(jaxpr, "eqns", ()))
    last_use: Dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if hasattr(v, "aval"):
                last_use[id(v)] = i
    live: Dict[int, int] = {}
    cur = 0
    peak = 0
    for i, eqn in enumerate(eqns):
        inner = 0
        subs = list(_sub_jaxprs(eqn))
        if subs:
            if eqn.primitive.name == "cond":
                inner = max(_liveness_peak(s, frozenset()) for s in subs)
            else:
                inner = sum(_liveness_peak(s, frozenset()) for s in subs)
        for v in eqn.outvars:
            if id(v) in outvars_credit or not hasattr(v, "aval"):
                continue
            b = _aval_bytes(v.aval)
            if id(v) not in live:
                live[id(v)] = b
                cur += b
        peak = max(peak, cur + inner)
        # Free every buffer whose last use was this eqn — including
        # outputs nothing ever reads (their one program point was the
        # production itself).
        for v in list(eqn.outvars) + list(eqn.invars):
            if last_use.get(id(v), -1) <= i and id(v) in live:
                cur -= live.pop(id(v))
    return peak


def _used_invar_positions(jaxpr) -> set:
    """Positions of the invars actually READ somewhere in the program or
    returned from it. jit compiles with ``keep_unused=False`` semantics —
    unused parameters are pruned before XLA prices them — so the
    analytic walk must prune them too. Usage propagates through
    call-like eqns whose single sub-jaxpr's invars align 1:1 with the
    eqn's (pjit/closed_call): an argument forwarded into a callee that
    never reads it is still unused. Non-aligned control flow
    (while/scan/cond offset their operand lists) conservatively counts
    every operand as used."""
    used: set = set()
    for eqn in jaxpr.eqns:
        # _sub_jaxprs may yield a ClosedJaxpr (pjit) — unwrap to the open
        # jaxpr, whose invars are positional.
        subs = [getattr(s, "jaxpr", s) for s in _sub_jaxprs(eqn)]
        if len(subs) == 1 and len(subs[0].invars) == len(eqn.invars):
            for k in _used_invar_positions(subs[0]):
                if hasattr(eqn.invars[k], "aval"):
                    used.add(id(eqn.invars[k]))
        else:
            for v in eqn.invars:
                if hasattr(v, "aval"):
                    used.add(id(v))
    used.update(id(v) for v in jaxpr.outvars if hasattr(v, "aval"))
    return {k for k, v in enumerate(jaxpr.invars) if id(v) in used}


def analytic_memory(closed, alias_bytes: int = 0,
                    shards: int = 1) -> Dict[str, int]:
    """The device-free twin of ``memory_analysis()``.

    ``argument``/``output`` come straight off the avals of the USED
    invars/outvars (jit prunes unused parameters before XLA prices
    them), divided by ``shards`` for multi-device programs —
    ``memory_analysis`` reports per-device bytes. ``const`` is the
    hoisted trace-constant payload (graph tables closed over by
    ``functools.partial`` builders): XLA folds these into the
    executable, so they appear in NO ``memory_analysis`` bucket — but
    they are resident on chip all the same, which is why the capacity
    planner prices ``const`` on top of the compiled peak. ``temp`` is
    the recursive last-use liveness peak — an upper bound (it does not
    model fusion), recorded for the planner's headroom estimate, kept
    OUT of the parity metric. ``interface = argument + output - alias``
    is the drift-gate metric: exact-by-construction unless the
    sharding/pruning assumptions the planner also relies on break."""
    jaxpr = closed.jaxpr
    shards = max(int(shards), 1)
    used = _used_invar_positions(jaxpr)
    argument = sum(_aval_bytes(v.aval)
                   for k, v in enumerate(jaxpr.invars)
                   if k in used) // shards
    const = sum(_aval_bytes(c) for c in closed.consts)
    output = sum(_aval_bytes(v.aval) for v in jaxpr.outvars
                 if hasattr(v, "aval")) // shards
    outset = frozenset(id(v) for v in jaxpr.outvars if hasattr(v, "aval"))
    temp = _liveness_peak(jaxpr, outset) // shards
    alias = min(int(alias_bytes) // shards, argument)
    return {"argument": argument, "output": output, "const": const,
            "temp": temp, "alias": alias,
            "interface": argument + output - alias}


def _alias_credit_bytes(hlo: str, invars) -> int:
    """Donated-buffer credit: bytes of every invar the compiled
    ``input_output_alias`` section names as a reused parameter — the
    donation audit's alias pairs, reused as the analytic model's ground
    truth. Parameter indices past the invar list (constant hoisting)
    are skipped rather than guessed."""
    credit = 0
    for m in _ALIAS_PARAM.finditer(_alias_section(hlo)):
        idx = int(m.group(1))  # graftlint: ignore[host-sync-in-loop] -- regex group over HLO text, no device values
        if 0 <= idx < len(invars) and hasattr(invars[idx], "aval"):
            credit += _aval_bytes(invars[idx].aval)
    return credit


# ------------------------------------------------------ compiled record


def collect_memory(traces: Sequence[Trace]) -> Dict[str, dict]:
    """AOT-compile every traced lowering and extract its memory record::

        {name: {"compiled": {argument, output, temp, alias, peak},
                "analytic": {argument, output, temp, alias, peak},
                "model_ratio": analytic_peak / compiled_peak}}

    Entries that failed to trace are skipped (ir-trace-error already
    fired). A compile failure records ``{"error": ...}`` (the ratchet
    reports it); a backend without ``memory_analysis()`` records
    ``{"skipped": MEM_UNAVAILABLE}`` — the degrade path, surfaced by
    the CLI, never a crash."""
    out: Dict[str, dict] = {}
    for trace in traces:
        if trace.error is not None:
            continue
        name = trace.entry.name
        try:
            fn, args = trace.entry.build()
            lowered = (
                fn.lower(*args) if hasattr(fn, "lower")
                # graftlint: ignore[jit-in-loop] -- AOT audit driver: each
                # iteration lowers a DIFFERENT entry exactly once; nothing
                # executes, so there is no compile cache to preserve.
                else jax.jit(fn).lower(*args))
            compiled = lowered.compile()
            ma = getattr(compiled, "memory_analysis", None)
            stats = ma() if callable(ma) else None
            if isinstance(stats, (list, tuple)):  # older jax: per device
                stats = stats[0] if stats else None
            if stats is None or not hasattr(stats, "temp_size_in_bytes"):
                out[name] = {"skipped": MEM_UNAVAILABLE}
                continue
            compiled_rec = {
                "argument": int(stats.argument_size_in_bytes),  # graftlint: ignore[host-sync-in-loop] -- memory_analysis() stats are host ints
                "output": int(stats.output_size_in_bytes),  # graftlint: ignore[host-sync-in-loop] -- same
                "temp": int(stats.temp_size_in_bytes),  # graftlint: ignore[host-sync-in-loop] -- same
                "alias": int(stats.alias_size_in_bytes),  # graftlint: ignore[host-sync-in-loop] -- same
            }
            compiled_rec["peak"] = (
                compiled_rec["argument"] + compiled_rec["output"]
                + compiled_rec["temp"] - compiled_rec["alias"])
            record = {"compiled": compiled_rec}
            if trace.jaxpr is not None:
                alias_credit = _alias_credit_bytes(
                    compiled.as_text(), trace.jaxpr.jaxpr.invars)
                analytic = analytic_memory(
                    trace.jaxpr, alias_credit,
                    shards=trace.entry.needs_devices)
                record["analytic"] = analytic
                have = (compiled_rec["argument"] + compiled_rec["output"]
                        - compiled_rec["alias"])
                if have > 0:
                    record["model_ratio"] = round(
                        analytic["interface"] / have, 4)
            out[name] = record
        except Exception as e:  # noqa: BLE001 — surfaced by the ratchet
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def mem_skipped(records: Dict[str, dict]) -> List[str]:
    """Names whose backend could not price memory (the degrade list)."""
    return sorted(n for n, r in records.items()
                  if r.get("skipped") == MEM_UNAVAILABLE)


# ----------------------------------------------------------- the ratchet


def default_membudgets_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "membudgets.json")


def load_membudgets(path: Optional[str] = None) -> dict:
    """The checked-in memory-budget document (``{}`` when absent — a
    repo without membudgets gates nothing until ``--write-membudgets``
    blesses)."""
    path = path or default_membudgets_path()
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def write_membudgets(records: Dict[str, dict], path: Optional[str] = None,
                     tolerance: float = DEFAULT_TOLERANCE,
                     capacity_model: Optional[dict] = None) -> str:
    """Bless the current memory records as the new baseline. The fitted
    capacity model (capacity.py coefficients) rides in the same file so
    ``capacity.plan`` extrapolates from checked-in, reviewed numbers."""
    import jaxlib

    path = path or default_membudgets_path()
    payload = {
        "schema": SCHEMA,
        "comment": ("graftmem static HBM budgets. compiled.* comes from "
                    "Compiled.memory_analysis() on the CPU backend; "
                    "analytic.* from the jaxpr buffer-liveness walk "
                    "(donation aliases credited from the compiled "
                    "input_output_alias pairs). CI fails on peak drift "
                    "past `tolerance` or analytic/compiled disagreement "
                    "past `model_tolerance`; bless deliberate changes "
                    "with `graftaudit --write-membudgets` and commit the "
                    "diff. `capacity_model` holds the fitted closed-form "
                    "coefficients capacity.plan extrapolates from."),
        "jaxlib": getattr(jaxlib, "__version__", "unknown"),
        "tolerance": tolerance,
        "model_tolerance": MODEL_TOLERANCE,
        "entries": {k: records[k] for k in sorted(records)
                    if "skipped" not in records[k]},
    }
    if capacity_model is not None:
        payload["capacity_model"] = capacity_model
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return path


def _mem_finding(rule: str, name: str, message: str,
                 severity: str) -> Finding:
    return Finding(severity=severity, file=name, line=0, col=0,
                   rule=rule, message=message)


def _class_of(name: str) -> str:
    """The shape-class suffix of a lowering name (for finding text —
    a stale row must say WHICH class's record went stale)."""
    cls = name.rsplit("@", 1)[-1] if "@" in name else "?"
    try:
        parse_shape_class(cls)
        return cls
    except ValueError:
        return "?"


def check_membudgets(records: Dict[str, dict], budgets: dict,
                     tolerance: Optional[float] = None,
                     skipped: Optional[Sequence[str]] = None
                     ) -> List[Finding]:
    """Current memory records vs the blessed membudgets. Fails on:
    compiled peak drift past tolerance (``ir-mem-regression``; shrink is
    P2), lowerings with no blessed record (``ir-mem-unbudgeted``),
    analytic-vs-compiled disagreement past ``MODEL_TOLERANCE``
    (``ir-mem-model-drift``), compile failures, and stale rows.

    ``skipped`` names lowerings this run could not audit — the device
    skip-list AND the memory_analysis-unavailable degrade list; their
    blessed rows are NOT stale."""
    entries = budgets.get("entries", {})
    if tolerance is None:
        tolerance = float(budgets.get("tolerance", DEFAULT_TOLERANCE))
    model_tol = float(budgets.get("model_tolerance", MODEL_TOLERANCE))
    skip = set(skipped or ()) | set(mem_skipped(records))
    out: List[Finding] = []
    for name, rec in sorted(records.items()):
        if rec.get("skipped") == MEM_UNAVAILABLE:
            continue
        if "error" in rec:
            out.append(_mem_finding(
                "ir-mem-regression", name,
                f"lowering failed to AOT-compile: {rec['error']} — the "
                "memory gate is off for it", "P1"))
            continue
        ratio = rec.get("model_ratio")
        if ratio is not None and abs(ratio - 1.0) > model_tol:
            out.append(_mem_finding(
                "ir-mem-model-drift", name,
                f"analytic liveness walk disagrees with "
                f"memory_analysis() by {ratio:.2f}x on interface bytes "
                f"(tolerance {model_tol:.0%}) — the capacity planner's "
                "closed-form extrapolation is untrustworthy for this "
                "entry; fix the model (analysis/ir/memory.py) or explain "
                "the compiled-side change", "P2"))
        budget = entries.get(name)
        if budget is None:
            out.append(_mem_finding(
                "ir-mem-unbudgeted", name,
                "new lowering with no blessed memory budget — run "
                "`graftaudit --write-membudgets` and commit "
                "membudgets.json", "P1"))
            continue
        if "error" in budget or "compiled" not in budget:
            out.append(_mem_finding(
                "ir-mem-regression", name,
                "blessed memory budget is a compile-error record — no "
                "bytes to ratchet against; re-bless with "
                "--write-membudgets once the lowering compiles", "P1"))
            continue
        have = rec["compiled"].get("peak", 0)
        want = budget["compiled"].get("peak", 0)
        if want > 0:
            r = float(have) / float(want)  # graftlint: ignore[host-sync-in-loop] -- budget JSON values, plain Python ints on the host
            if r > 1.0 + tolerance:
                out.append(_mem_finding(
                    "ir-mem-regression", name,
                    f"compiled peak memory grew {r:.2f}x over budget "
                    f"({have} vs {want} bytes, tolerance "
                    f"{tolerance:.0%}) — explain the regression or bless "
                    "it with --write-membudgets", "P1"))
            elif r < 1.0 - tolerance:
                out.append(_mem_finding(
                    "ir-mem-regression", name,
                    f"compiled peak memory shrank to {r:.2f}x of budget "
                    f"({have} vs {want} bytes) — nice, but bless it "
                    "(--write-membudgets) so the ratchet holds the new "
                    "level", "P2"))
    stale = sorted(set(entries) - set(records) - skip)
    for name in stale:
        out.append(_mem_finding(
            "ir-mem-regression", name,
            f"memory budget entry for a lowering the registry no longer "
            f"produces (shape-class {_class_of(name)}) — regenerate "
            "membudgets.json (--write-membudgets) so the file matches "
            "HEAD", "P2"))
    return sorted(out)
