"""graftaudit cost ratchet: compiled costs, pinned and gated per PR.

``Compiled.cost_analysis()`` prices a program (flops, bytes accessed)
without executing it — deterministic for a fixed (program, backend,
jaxlib), which makes it a RATCHET: persist the per-lowering costs in a
checked-in ``budgets.json``, and CI fails on unexplained growth with zero
benchmark time. The same record pins the collective census (ppermute /
psum / all_gather occurrences and the estimated ICI bytes of the ring
model in registry.py, cross-checked against the compiled HLO through the
commviz parser) — collective drift is how multi-chip perf regressions
arrive, one extra psum at a time.

Baseline semantics mirror graftlint's: the checked-in file grandfathers
HEAD, ``graftaudit --write-budgets`` blesses a deliberate change (commit
the diff — it IS the review artifact), stale entries fail so the file
can only shrink by being regenerated. Growth within ``tolerance``
(default 20%, stored in the file) absorbs backend jitter across jaxlib
upgrades; the recorded jaxlib version marks when a wholesale re-bless is
the right response to a noisy diff.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import jax

from p2pnetwork_tpu.analysis.core import Finding
from p2pnetwork_tpu.analysis.ir.registry import Trace, parse_shape_class

__all__ = ["collect_costs", "load_budgets", "write_budgets",
           "check_budgets", "default_budgets_path", "DEFAULT_TOLERANCE"]

SCHEMA = "graftaudit-budgets-v1"
DEFAULT_TOLERANCE = 0.20


def default_budgets_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "budgets.json")


def _hlo_collective_bytes(hlo: str) -> int:
    """Total collective payload bytes of a compiled module, through the
    one HLO collective parser the repo already trusts (commviz)."""
    from p2pnetwork_tpu.parallel import commviz

    return sum(c[3] for c in commviz.collectives(hlo))


def collect_costs(traces: Sequence[Trace]) -> Dict[str, dict]:
    """AOT-compile every traced lowering and extract its cost record:
    ``{name: {flops, bytes, collectives, ici_bytes_est, ici_bytes_hlo}}``.
    Entries that failed to trace are skipped (ir-trace-error already
    fired); a compile failure records ``{"error": ...}`` so the ratchet
    reports it instead of silently ungating the entry."""
    out: Dict[str, dict] = {}
    for trace in traces:
        if trace.error is not None:
            continue
        name = trace.entry.name
        try:
            fn, args = trace.entry.build()
            lowered = (
                fn.lower(*args) if hasattr(fn, "lower")
                # graftlint: ignore[jit-in-loop] -- AOT audit driver: each
                # iteration lowers a DIFFERENT entry exactly once; nothing
                # executes, so there is no compile cache to preserve.
                else jax.jit(fn).lower(*args))
            compiled = lowered.compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax: one per device
                ca = ca[0] if ca else {}
            record = {
                # graftlint: ignore[host-sync-in-loop] -- cost_analysis
                # returns a host dict of Python floats; no device values.
                "flops": float(ca.get("flops", -1.0)),
                "bytes": float(ca.get("bytes accessed", -1.0)),
                "collectives": dict(sorted(trace.collectives.items())),
                "ici_bytes_est": int(trace.ici_bytes_est),
            }
            if trace.collectives:
                record["ici_bytes_hlo"] = _hlo_collective_bytes(
                    compiled.as_text())
            out[name] = record
        except Exception as e:  # noqa: BLE001 — surfaced by the ratchet
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def load_budgets(path: Optional[str] = None) -> dict:
    """The checked-in budget document (``{}`` when absent — a repo
    without budgets gates nothing until ``--write-budgets`` blesses)."""
    path = path or default_budgets_path()
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def write_budgets(costs: Dict[str, dict], path: Optional[str] = None,
                  tolerance: float = DEFAULT_TOLERANCE) -> str:
    """Bless the current costs as the new budget baseline."""
    import jaxlib

    path = path or default_budgets_path()
    payload = {
        "schema": SCHEMA,
        "comment": ("graftaudit compiled-cost budgets. flops/bytes come "
                    "from Compiled.cost_analysis() on the CPU backend; "
                    "collectives/ici bytes from the jaxpr census and the "
                    "compiled HLO. CI fails on growth past `tolerance` or "
                    "any collective-count change; bless deliberate "
                    "changes with `graftaudit --write-budgets` and commit "
                    "the diff."),
        "jaxlib": getattr(jaxlib, "__version__", "unknown"),
        "tolerance": tolerance,
        "entries": {k: costs[k] for k in sorted(costs)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return path


def _ratchet(name: str, message: str, severity: str = "P1") -> Finding:
    return Finding(severity=severity, file=name, line=0, col=0,
                   rule="ir-cost-ratchet", message=message)


def _class_of(name: str) -> str:
    """The shape-class suffix of a lowering name. Stale-row findings must
    say WHICH class's record went stale — `or/segment` exists at both
    ws1k and ba1k, and the bare name is ambiguous between them."""
    cls = name.rsplit("@", 1)[-1] if "@" in name else "?"
    try:
        parse_shape_class(cls)
        return cls
    except ValueError:
        return "?"


def check_budgets(costs: Dict[str, dict], budgets: dict,
                  tolerance: Optional[float] = None,
                  skipped: Optional[Sequence[str]] = None) -> List[Finding]:
    """Current costs vs the blessed budgets. Fails on: growth of flops /
    bytes / ICI bytes past tolerance, ANY collective-count change, new
    lowerings without a budget, stale budget entries, and compile
    failures. Shrink past tolerance also fails — a win is blessed the
    same way as a regression, so the file keeps matching HEAD.

    ``skipped`` names lowerings this run could not audit (a degraded
    host pinned fewer devices than the entry needs); their budget
    entries are NOT stale — flagging them would tell the operator to
    regenerate a budgets.json missing the sharded entries."""
    entries = budgets.get("entries", {})
    if tolerance is None:
        tolerance = float(budgets.get("tolerance", DEFAULT_TOLERANCE))
    out: List[Finding] = []
    for name, cost in sorted(costs.items()):
        if "error" in cost:
            out.append(_ratchet(
                name, f"lowering failed to AOT-compile: {cost['error']} — "
                      "the cost gate is off for it"))
            continue
        budget = entries.get(name)
        if budget is None:
            out.append(_ratchet(
                name, "new lowering with no blessed budget — run "
                      "`graftaudit --write-budgets` and commit "
                      "budgets.json", severity="P2"))
            continue
        if "error" in budget:
            # A blessed error record has no metrics to compare against —
            # left alone it would silently un-gate this lowering forever.
            out.append(_ratchet(
                name, "blessed budget is a compile-error record — no "
                      "metrics to ratchet against; re-bless with "
                      "--write-budgets once the lowering compiles"))
            continue
        for metric in ("flops", "bytes", "ici_bytes_est", "ici_bytes_hlo"):
            have, want = cost.get(metric), budget.get(metric)
            if have is None or want is None or want <= 0:
                continue
            ratio = float(have) / float(want)  # graftlint: ignore[host-sync-in-loop] -- budget JSON values, plain Python floats on the host
            if ratio > 1.0 + tolerance:
                out.append(_ratchet(
                    name, f"{metric} grew {ratio:.2f}x over budget "
                          f"({have:.0f} vs {want:.0f}, tolerance "
                          f"{tolerance:.0%}) — explain the regression or "
                          "bless it with --write-budgets"))
            elif ratio < 1.0 - tolerance:
                out.append(_ratchet(
                    name, f"{metric} shrank to {ratio:.2f}x of budget "
                          f"({have:.0f} vs {want:.0f}) — nice, but bless "
                          "it (--write-budgets) so the ratchet holds the "
                          "new level", severity="P2"))
        if dict(cost.get("collectives", {})) != dict(
                budget.get("collectives", {})):
            out.append(_ratchet(
                name, f"collective census changed: "
                      f"{budget.get('collectives', {})} -> "
                      f"{cost.get('collectives', {})} — multi-chip "
                      "traffic structure drifted; verify against the "
                      "commviz comm budgets, then bless"))
    stale = sorted(set(entries) - set(costs) - set(skipped or ()))
    for name in stale:
        out.append(_ratchet(
            name, f"budget entry for a lowering the registry no longer "
                  f"produces (shape-class {_class_of(name)}) — regenerate "
                  "budgets.json (--write-budgets) so the file matches "
                  "HEAD", severity="P2"))
    return sorted(out)
