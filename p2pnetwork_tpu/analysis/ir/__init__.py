"""graftaudit — IR-level static audit of the lowering zoo.

graftlint (:mod:`p2pnetwork_tpu.analysis`) polices Python source, but the
failure modes that actually burn TPU time live one layer down, in what the
lowering zoo *compiles to*: a silently dropped donation double-buffers the
carry for a whole run, an f64 widening doubles bandwidth, a broken frontier
compaction gathers the whole padded edge set every round, and collective
traffic drifts PR over PR — none of it visible to an AST rule and none
exercised by unit tests. graftaudit closes that gap statically, with **zero
device time**: everything runs under ``JAX_PLATFORMS=cpu`` via abstract
tracing (``jax.make_jaxpr`` / ``jax.eval_shape``) and AOT lowering
(``jit(f).lower(...).compile()`` on the CPU backend).

Four planes, one CLI (``graftaudit``, beside ``graftlint``):

- **Lowering registry** (:mod:`.registry`) — every propagation variant
  (``ops/segment.py`` segment/gather, ``ops/blocked.py``, ``ops/skew.py``,
  ``ops/frontier.py``, ``ops/bitset.py`` via the packed flood step, the
  ``parallel/sharded.py`` ppermute coverage loop, the engine coverage
  loop) × canonical shape-classes, traced to jaxprs and abstract output
  signatures.
- **Jaxpr rules** (:mod:`.rules`) — forbidden host callbacks, f64
  ``convert_element_type`` widenings / f64 values, gather/scatter slot
  counts vs the frontier budget, plus the cross-lowering abstract-
  signature **parity gate** (all lowerings of one op must agree on
  ``eval_shape`` signatures).
- **Donation audit** (:mod:`.donation`) — AOT-compiles the engine's
  state-carry steps and asserts the compiled executable's
  ``input_output_alias`` actually aliases every carry leaf, so donation
  can never again be dropped silently.
- **Cost ratchet** (:mod:`.budgets`) — ``Compiled.cost_analysis()``
  flops/bytes and a collective census (ppermute/psum/all_gather counts +
  estimated ICI bytes, compiled bytes cross-checked through the commviz
  parser) per (lowering, shape-class), persisted in the checked-in
  ``budgets.json`` with graftlint-style baseline semantics — CI fails on
  unexplained cost growth without running a single benchmark.

Findings ride the graftlint machinery (:mod:`p2pnetwork_tpu.analysis.core`
``Finding`` records, severity order, baseline fingerprinting), so the two
gates render, sort, and grandfather identically.
"""

__all__ = ["Lowering", "Trace", "all_lowerings", "shape_class",
           "trace_lowering"]


def __getattr__(name):
    # Lazy re-exports (PEP 562): the device-free guarantee depends on the
    # CLI pinning JAX_PLATFORMS BEFORE jax first imports (jax captures the
    # env var at import, not at backend init), and `python -m ...ir` /
    # the console script both execute this module before __main__.main()
    # can pin — so importing this package must not touch registry/jax.
    if name in __all__:
        from p2pnetwork_tpu.analysis.ir import registry

        return getattr(registry, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
