"""graftaudit CLI: ``python -m p2pnetwork_tpu.analysis.ir`` / ``graftaudit``.

Exit codes mirror graftlint: 0 — no non-baselined findings; 1 — findings
to fix; 2 — bad invocation. The audit is device-free by construction:
this module pins ``JAX_PLATFORMS=cpu`` and the 8-way virtual host
platform BEFORE jax initializes, so the full registry — the sharded
ppermute path included — runs in CPU-only CI.

Typical invocations::

    graftaudit                       # the CI gate (rules + parity +
                                     #   donation + cost + memory ratchets)
    graftaudit --json                # machine-readable document
    graftaudit --no-cost             # skip AOT compiles (fast rule pass)
    graftaudit --write-budgets       # bless current costs into budgets.json
    graftaudit --write-membudgets    # bless memory records + refit the
                                     #   capacity model into membudgets.json
    graftaudit --plan                # the W=313 / 1M-node north-star
                                     #   capacity plan (no building)
    graftaudit --plan nodes=200000,lanes=4096,hbm_gb=8
    graftaudit --list-lowerings      # registry table
    graftaudit --list-rules          # rule table
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _pin_cpu_platform() -> None:
    """Device-free guarantee: the audit must not grab a TPU (or hang on a
    tunneled backend) and must see the 8-device virtual mesh. Only
    effective before jax's backend initializes — the conftest does the
    same dance for the test suite."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftaudit",
        description=("IR-level static audit of the lowering zoo: jaxpr "
                     "rules, signature parity, donation aliasing, and the "
                     "compiled-cost ratchet — all device-free (CPU-only "
                     "abstract tracing + AOT lowering)."))
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (one JSON document with "
                        "findings, census, and cost tables)")
    p.add_argument("--budgets", default=None, metavar="PATH",
                   help="budgets file (default: the package's checked-in "
                        "analysis/ir/budgets.json)")
    p.add_argument("--write-budgets", action="store_true",
                   help="bless the current compiled costs into the "
                        "budgets file and exit 0 (commit the diff)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="grandfathered-findings baseline (default: "
                        "analysis/ir/baseline.json; absent = empty)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather every current finding into the "
                        "baseline file and exit 0")
    p.add_argument("--no-cost", action="store_true",
                   help="skip AOT compilation (no cost ratchet, no "
                        "donation audit, no memory ratchet) — the fast "
                        "jaxpr-rule pass")
    p.add_argument("--membudgets", default=None, metavar="PATH",
                   help="memory-budgets file (default: the package's "
                        "checked-in analysis/ir/membudgets.json)")
    p.add_argument("--write-membudgets", action="store_true",
                   help="bless the current memory records (and refit the "
                        "capacity-model coefficients — two extra "
                        "full-registry AOT passes) into the membudgets "
                        "file and exit 0 (commit the diff)")
    p.add_argument("--no-mem", action="store_true",
                   help="skip the memory ratchet (membudgets gate) while "
                        "keeping the cost pass")
    p.add_argument("--plan", nargs="?", const="northstar", default=None,
                   metavar="SPEC",
                   help="print a capacity plan from the checked-in "
                        "coefficients and exit — no building, no "
                        "compiling. SPEC is k=v[,k=v...] over nodes, "
                        "lanes, hbm_gb, headroom, entry; bare --plan is "
                        "the north-star 1M-node / 10k-lane serving shape")
    p.add_argument("--tolerance", type=float, default=None,
                   help="cost-growth tolerance override (fraction; "
                        "default: the value stored in budgets.json)")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help="run only these jaxpr rule ids (parity/donation/"
                        "ratchet gates still run unless skipped)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--list-lowerings", action="store_true",
                   help="print the lowering registry and exit")
    return p


def _parse_plan_spec(spec: str) -> dict:
    """``nodes=200000,lanes=4096,hbm_gb=8`` -> capacity.plan kwargs.
    Bare ``--plan`` (or any omitted key) falls back to the north-star
    serving shape: 1M nodes, 10k lanes (W=313 words), 16 GiB/chip."""
    kw: dict = {"n_nodes": 1_000_000, "lanes": 10_016}
    if spec and spec != "northstar":
        for part in spec.split(","):
            k, sep, v = part.partition("=")
            k = k.strip()
            if not sep or not k:
                raise ValueError(f"bad --plan token {part!r} "
                                 "(want k=v[,k=v...])")
            if k == "nodes":
                kw["n_nodes"] = int(v)  # graftlint: ignore[host-sync-in-loop] -- CLI string parsing, no device values
            elif k == "lanes":
                kw["lanes"] = int(v)  # graftlint: ignore[host-sync-in-loop] -- CLI string parsing
            elif k == "hbm_gb":
                kw["per_chip_hbm_bytes"] = float(v) * 1024**3  # graftlint: ignore[host-sync-in-loop] -- CLI string parsing
            elif k == "headroom":
                kw["headroom"] = float(v)  # graftlint: ignore[host-sync-in-loop] -- CLI string parsing
            elif k == "entry":
                kw["entry"] = v.strip()
            else:
                raise ValueError(
                    f"unknown --plan key {k!r} (known: nodes, lanes, "
                    "hbm_gb, headroom, entry)")
    return kw


def _render_plan(doc: dict) -> None:
    gib = 1024**3
    print(f"capacity plan — {doc['entry']}")
    print(f"  overlay   {doc['n_nodes']:,} nodes (padded {doc['n_pad']:,} "
          f"nodes / {doc['e_pad']:,} edge slots)")
    print(f"  lanes     {doc['lanes']:,} ({doc['lane_words']} u32 words)")
    print(f"  global    {doc['global_bytes'] / gib:.2f} GiB modeled "
          "resident bytes")
    print(f"  chip HBM  {doc['per_chip_hbm_bytes'] / gib:.1f} GiB "
          f"(headroom {doc['headroom']:.0%})")
    for row in doc["per_chip"]:
        mark = "fits" if row["fits"] else "OVER"
        print(f"    shards={row['shards']:<5d} "
              f"{row['per_chip_bytes'] / gib:7.2f} GiB/chip  {mark}")
    rec = doc["recommended_shards"]
    print("  recommend "
          + (f"{rec} shard(s)" if rec else
             "NOTHING in the candidate list fits — raise shards or HBM"))


def _default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    _pin_cpu_platform()

    from p2pnetwork_tpu.analysis import core
    from p2pnetwork_tpu.analysis.ir import budgets as B
    from p2pnetwork_tpu.analysis.ir import capacity as C
    from p2pnetwork_tpu.analysis.ir import memory as M
    from p2pnetwork_tpu.analysis.ir import donation, registry, rules

    if args.plan is not None:
        try:
            doc = C.plan(**_parse_plan_spec(args.plan))
        except ValueError as e:
            print(f"graftaudit: {e}", file=sys.stderr)
            return 2
        if args.as_json:
            print(json.dumps(doc, indent=1))
        else:
            _render_plan(doc)
        return 0

    if args.list_rules:
        table = rules.all_ir_rules()
        width = max(len(r) for r in table)
        for rule in sorted(table.values(), key=lambda r: (r.severity, r.id)):
            print(f"{rule.id:<{width}}  {rule.severity}  {rule.doc}")
        print(f"{'ir-sig-parity':<{width}}  P0  cross-lowering "
              "eval_shape signature parity gate (rules.parity_findings)")
        print(f"{'ir-donation-dropped':<{width}}  P0  compiled "
              "input_output_alias must cover every donated carry leaf "
              "(donation.audit_donation)")
        print(f"{'ir-cost-ratchet':<{width}}  P1  compiled cost vs the "
              "blessed budgets.json (budgets.check_budgets)")
        print(f"{'ir-mem-regression':<{width}}  P1  compiled peak memory "
              "vs the blessed membudgets.json (memory.check_membudgets; "
              "shrink past tolerance is P2)")
        print(f"{'ir-mem-unbudgeted':<{width}}  P1  lowering with no "
              "blessed memory budget (memory.check_membudgets)")
        print(f"{'ir-mem-model-drift':<{width}}  P2  analytic liveness "
              "walk vs memory_analysis() disagree past the model "
              "tolerance (memory.check_membudgets)")
        return 0

    entries = registry.all_lowerings()
    import jax

    n_dev = len(jax.devices())
    runnable = [e for e in entries if e.needs_devices <= n_dev]
    skipped = [e for e in entries if e.needs_devices > n_dev]
    if skipped:
        # Only possible when a host imported jax before this CLI could
        # pin the virtual mesh — CI never hits this, humans should know.
        print(f"graftaudit: {len(skipped)} lowering(s) need "
              f">{n_dev} devices and were skipped: "
              + ", ".join(e.name for e in skipped), file=sys.stderr)

    if args.list_lowerings:
        width = max(len(e.name) for e in entries)
        for e in entries:
            mark = "" if e in runnable else "  [skipped: needs "\
                f"{e.needs_devices} devices]"
            parity = "parity" if e.parity else "      "
            print(f"{e.name:<{width}}  {parity}  {e.doc or e.op}{mark}")
        return 0

    selected = rules.all_ir_rules()
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in wanted if r not in selected]
        if unknown:
            print(f"graftaudit: unknown rule(s): {', '.join(unknown)} "
                  "(try --list-rules)", file=sys.stderr)
            return 2
        selected = {r: selected[r] for r in wanted}

    traces = [registry.trace_lowering(e) for e in runnable]
    findings = rules.run_ir_rules(traces, selected)
    findings += rules.parity_findings(traces)

    costs: Dict[str, dict] = {}
    if not args.no_cost:
        findings += donation.audit_donation()
        costs = B.collect_costs(traces)
        if args.write_budgets:
            broken = sorted(n for n, c in costs.items() if "error" in c)
            if broken:
                # Blessing an error record would permanently un-gate that
                # lowering: check_budgets has no metrics to compare
                # against, so later regressions pass silently.
                print("graftaudit: refusing --write-budgets while "
                      "lowering(s) fail to compile: "
                      + ", ".join(broken)
                      + " — fix the entries, then bless", file=sys.stderr)
                return 2
            if skipped:
                # A degraded run must not bless: the written file would
                # drop the sharded entries and fail the next full CI run
                # as "new lowering with no blessed budget".
                print("graftaudit: refusing --write-budgets on a degraded "
                      "run (skipped: "
                      + ", ".join(e.name for e in skipped)
                      + ") — rerun where graftaudit can pin the full "
                      "virtual mesh (no prior jax import)",
                      file=sys.stderr)
                return 2
            # A re-bless keeps the stored tolerance unless explicitly
            # overridden — check_budgets honors the stored value, so the
            # bless path must not silently reset it to the default.
            stored = B.load_budgets(args.budgets).get("tolerance")
            tol = (args.tolerance if args.tolerance is not None
                   else stored if stored is not None
                   else B.DEFAULT_TOLERANCE)
            path = B.write_budgets(costs, args.budgets, tolerance=tol)
            print(f"graftaudit: wrote {len(costs)} budget entr(ies) to "
                  f"{path}")
            return 0
        findings += B.check_budgets(costs, B.load_budgets(args.budgets),
                                    tolerance=args.tolerance,
                                    skipped=[e.name for e in skipped])
    elif args.write_budgets:
        print("graftaudit: --write-budgets needs the compile pass; drop "
              "--no-cost", file=sys.stderr)
        return 2

    mem_records: Dict[str, dict] = {}
    mem_skip: List[str] = []
    if not args.no_cost and not args.no_mem:
        mem_records = M.collect_memory(traces)
        mem_skip = M.mem_skipped(mem_records)
        if mem_skip:
            # The memory_analysis-unavailable degrade list — loud, like
            # the <8-device skip list, never a crash.
            print(f"graftaudit: memory plane degraded — {len(mem_skip)} "
                  "lowering(s) without memory_analysis() support: "
                  + ", ".join(mem_skip), file=sys.stderr)
        if args.write_membudgets:
            broken = sorted(n for n, r in mem_records.items()
                            if "error" in r)
            if broken:
                # Blessing an error record would permanently un-gate the
                # lowering — no bytes to ratchet against.
                print("graftaudit: refusing --write-membudgets while "
                      "lowering(s) fail to compile: " + ", ".join(broken)
                      + " — fix the entries, then bless", file=sys.stderr)
                return 2
            if skipped or mem_skip:
                # A degraded run (missing devices OR a backend that
                # cannot price memory) must not bless: the written file
                # would drop those entries and fail the next full run as
                # "no blessed memory budget".
                degraded = ([e.name for e in skipped] + mem_skip)
                print("graftaudit: refusing --write-membudgets on a "
                      "degraded run (skipped: " + ", ".join(degraded)
                      + ") — rerun where the full registry prices",
                      file=sys.stderr)
                return 2
            stored = M.load_membudgets(args.membudgets).get("tolerance")
            tol = (args.tolerance if args.tolerance is not None
                   else stored if stored is not None
                   else M.DEFAULT_TOLERANCE)
            print("graftaudit: refitting the capacity model (two extra "
                  "full-registry AOT passes — minutes, not seconds)",
                  file=sys.stderr)
            cap = C.fit_capacity_model(mem_records)
            path = M.write_membudgets(mem_records, args.membudgets,
                                      tolerance=tol, capacity_model=cap)
            print(f"graftaudit: wrote {len(mem_records)} memory budget "
                  f"entr(ies) + {len(cap.get('entries', {}))} capacity "
                  f"fit(s) to {path}")
            return 0
        findings += M.check_membudgets(
            mem_records, M.load_membudgets(args.membudgets),
            tolerance=args.tolerance,
            skipped=[e.name for e in skipped])
    elif args.write_membudgets:
        print("graftaudit: --write-membudgets needs the compile pass; "
              "drop --no-cost/--no-mem", file=sys.stderr)
        return 2

    findings = sorted(findings)
    baseline_path = args.baseline or _default_baseline_path()
    if args.write_baseline:
        path = core.write_baseline(findings, {}, baseline_path)
        print(f"graftaudit: wrote {len(findings)} finding(s) to {path}")
        return 0
    baseline = core.load_baseline(baseline_path)
    new, grandfathered = core.apply_baseline(findings, {}, baseline)

    census = {t.entry.name: {"collectives": t.collectives,
                             "ici_bytes_est": t.ici_bytes_est}
              for t in traces if t.collectives}
    if args.as_json:
        doc = {
            "findings": [f.to_json() for f in new],
            "baselined": len(grandfathered),
            "lowerings": [t.entry.name for t in traces],
            "skipped": [e.name for e in skipped],
            "census": census,
            "costs": costs,
            "memory": mem_records,
            "mem_skipped": mem_skip,
            "ok": not new,
        }
        print(json.dumps(doc, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if new:
        counts: Dict[str, int] = {}
        for f in new:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        summary = ", ".join(f"{n} {sev}" for sev, n in sorted(counts.items()))
        print(f"graftaudit: {len(new)} finding(s) ({summary}); "
              f"{len(grandfathered)} baselined")
        return 1
    suffix = f" ({len(grandfathered)} baselined)" if grandfathered else ""
    mem_note = ""
    if mem_records:
        priced = len(mem_records) - len(mem_skip)
        mem_note = f", {priced} memory-ratcheted"
        if mem_skip:
            mem_note += f" ({len(mem_skip)} mem-skipped)"
    print(f"graftaudit: clean{suffix} — {len(traces)} lowering(s) audited"
          + ("" if args.no_cost else
             f", {len(costs)} cost-ratcheted{mem_note}, donation verified"))
    return 0


def _cli() -> int:
    try:
        return main()
    except BrokenPipeError:
        return 0


if __name__ == "__main__":
    sys.exit(_cli())
