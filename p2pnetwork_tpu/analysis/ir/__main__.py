"""graftaudit CLI: ``python -m p2pnetwork_tpu.analysis.ir`` / ``graftaudit``.

Exit codes mirror graftlint: 0 — no non-baselined findings; 1 — findings
to fix; 2 — bad invocation. The audit is device-free by construction:
this module pins ``JAX_PLATFORMS=cpu`` and the 8-way virtual host
platform BEFORE jax initializes, so the full registry — the sharded
ppermute path included — runs in CPU-only CI.

Typical invocations::

    graftaudit                       # the CI gate (rules + parity +
                                     #   donation + cost ratchet)
    graftaudit --json                # machine-readable document
    graftaudit --no-cost             # skip AOT compiles (fast rule pass)
    graftaudit --write-budgets       # bless current costs into budgets.json
    graftaudit --list-lowerings      # registry table
    graftaudit --list-rules          # rule table
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _pin_cpu_platform() -> None:
    """Device-free guarantee: the audit must not grab a TPU (or hang on a
    tunneled backend) and must see the 8-device virtual mesh. Only
    effective before jax's backend initializes — the conftest does the
    same dance for the test suite."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftaudit",
        description=("IR-level static audit of the lowering zoo: jaxpr "
                     "rules, signature parity, donation aliasing, and the "
                     "compiled-cost ratchet — all device-free (CPU-only "
                     "abstract tracing + AOT lowering)."))
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (one JSON document with "
                        "findings, census, and cost tables)")
    p.add_argument("--budgets", default=None, metavar="PATH",
                   help="budgets file (default: the package's checked-in "
                        "analysis/ir/budgets.json)")
    p.add_argument("--write-budgets", action="store_true",
                   help="bless the current compiled costs into the "
                        "budgets file and exit 0 (commit the diff)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="grandfathered-findings baseline (default: "
                        "analysis/ir/baseline.json; absent = empty)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather every current finding into the "
                        "baseline file and exit 0")
    p.add_argument("--no-cost", action="store_true",
                   help="skip AOT compilation (no cost ratchet, no "
                        "donation audit) — the fast jaxpr-rule pass")
    p.add_argument("--tolerance", type=float, default=None,
                   help="cost-growth tolerance override (fraction; "
                        "default: the value stored in budgets.json)")
    p.add_argument("--rules", default=None, metavar="ID[,ID...]",
                   help="run only these jaxpr rule ids (parity/donation/"
                        "ratchet gates still run unless skipped)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--list-lowerings", action="store_true",
                   help="print the lowering registry and exit")
    return p


def _default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    _pin_cpu_platform()

    from p2pnetwork_tpu.analysis import core
    from p2pnetwork_tpu.analysis.ir import budgets as B
    from p2pnetwork_tpu.analysis.ir import donation, registry, rules

    if args.list_rules:
        table = rules.all_ir_rules()
        width = max(len(r) for r in table)
        for rule in sorted(table.values(), key=lambda r: (r.severity, r.id)):
            print(f"{rule.id:<{width}}  {rule.severity}  {rule.doc}")
        print(f"{'ir-sig-parity':<{width}}  P0  cross-lowering "
              "eval_shape signature parity gate (rules.parity_findings)")
        print(f"{'ir-donation-dropped':<{width}}  P0  compiled "
              "input_output_alias must cover every donated carry leaf "
              "(donation.audit_donation)")
        print(f"{'ir-cost-ratchet':<{width}}  P1  compiled cost vs the "
              "blessed budgets.json (budgets.check_budgets)")
        return 0

    entries = registry.all_lowerings()
    import jax

    n_dev = len(jax.devices())
    runnable = [e for e in entries if e.needs_devices <= n_dev]
    skipped = [e for e in entries if e.needs_devices > n_dev]
    if skipped:
        # Only possible when a host imported jax before this CLI could
        # pin the virtual mesh — CI never hits this, humans should know.
        print(f"graftaudit: {len(skipped)} lowering(s) need "
              f">{n_dev} devices and were skipped: "
              + ", ".join(e.name for e in skipped), file=sys.stderr)

    if args.list_lowerings:
        width = max(len(e.name) for e in entries)
        for e in entries:
            mark = "" if e in runnable else "  [skipped: needs "\
                f"{e.needs_devices} devices]"
            parity = "parity" if e.parity else "      "
            print(f"{e.name:<{width}}  {parity}  {e.doc or e.op}{mark}")
        return 0

    selected = rules.all_ir_rules()
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in wanted if r not in selected]
        if unknown:
            print(f"graftaudit: unknown rule(s): {', '.join(unknown)} "
                  "(try --list-rules)", file=sys.stderr)
            return 2
        selected = {r: selected[r] for r in wanted}

    traces = [registry.trace_lowering(e) for e in runnable]
    findings = rules.run_ir_rules(traces, selected)
    findings += rules.parity_findings(traces)

    costs: Dict[str, dict] = {}
    if not args.no_cost:
        findings += donation.audit_donation()
        costs = B.collect_costs(traces)
        if args.write_budgets:
            broken = sorted(n for n, c in costs.items() if "error" in c)
            if broken:
                # Blessing an error record would permanently un-gate that
                # lowering: check_budgets has no metrics to compare
                # against, so later regressions pass silently.
                print("graftaudit: refusing --write-budgets while "
                      "lowering(s) fail to compile: "
                      + ", ".join(broken)
                      + " — fix the entries, then bless", file=sys.stderr)
                return 2
            if skipped:
                # A degraded run must not bless: the written file would
                # drop the sharded entries and fail the next full CI run
                # as "new lowering with no blessed budget".
                print("graftaudit: refusing --write-budgets on a degraded "
                      "run (skipped: "
                      + ", ".join(e.name for e in skipped)
                      + ") — rerun where graftaudit can pin the full "
                      "virtual mesh (no prior jax import)",
                      file=sys.stderr)
                return 2
            # A re-bless keeps the stored tolerance unless explicitly
            # overridden — check_budgets honors the stored value, so the
            # bless path must not silently reset it to the default.
            stored = B.load_budgets(args.budgets).get("tolerance")
            tol = (args.tolerance if args.tolerance is not None
                   else stored if stored is not None
                   else B.DEFAULT_TOLERANCE)
            path = B.write_budgets(costs, args.budgets, tolerance=tol)
            print(f"graftaudit: wrote {len(costs)} budget entr(ies) to "
                  f"{path}")
            return 0
        findings += B.check_budgets(costs, B.load_budgets(args.budgets),
                                    tolerance=args.tolerance,
                                    skipped=[e.name for e in skipped])
    elif args.write_budgets:
        print("graftaudit: --write-budgets needs the compile pass; drop "
              "--no-cost", file=sys.stderr)
        return 2

    findings = sorted(findings)
    baseline_path = args.baseline or _default_baseline_path()
    if args.write_baseline:
        path = core.write_baseline(findings, {}, baseline_path)
        print(f"graftaudit: wrote {len(findings)} finding(s) to {path}")
        return 0
    baseline = core.load_baseline(baseline_path)
    new, grandfathered = core.apply_baseline(findings, {}, baseline)

    census = {t.entry.name: {"collectives": t.collectives,
                             "ici_bytes_est": t.ici_bytes_est}
              for t in traces if t.collectives}
    if args.as_json:
        doc = {
            "findings": [f.to_json() for f in new],
            "baselined": len(grandfathered),
            "lowerings": [t.entry.name for t in traces],
            "skipped": [e.name for e in skipped],
            "census": census,
            "costs": costs,
            "ok": not new,
        }
        print(json.dumps(doc, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if new:
        counts: Dict[str, int] = {}
        for f in new:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        summary = ", ".join(f"{n} {sev}" for sev, n in sorted(counts.items()))
        print(f"graftaudit: {len(new)} finding(s) ({summary}); "
              f"{len(grandfathered)} baselined")
        return 1
    suffix = f" ({len(grandfathered)} baselined)" if grandfathered else ""
    print(f"graftaudit: clean{suffix} — {len(traces)} lowering(s) audited"
          + ("" if args.no_cost else
             f", {len(costs)} cost-ratcheted, donation verified"))
    return 0


def _cli() -> int:
    try:
        return main()
    except BrokenPipeError:
        return 0


if __name__ == "__main__":
    sys.exit(_cli())
