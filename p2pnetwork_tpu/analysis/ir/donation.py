"""graftaudit donation audit: donated carries must actually alias.

PR 3 made the engine donate the state carry by default — at 10M nodes the
donated predicates are tens of MB of HBM that would otherwise
double-buffer for a whole run. But donation fails SILENTLY: a refactor
that drops ``donate_argnames``, or an argument change that makes XLA
refuse the alias (dtype/layout mismatch), compiles and runs bit-identically
— just slower and twice as heavy. graftlint's ``carry-no-donate`` catches
the missing *kwarg* in source; this module catches the dropped *effect* in
the compiled artifact, where it is ground truth:

- the **lowered MLIR** carries one ``tf.aliasing_output`` /
  ``jax.buffer_donor`` attribute per donated input — proof jax REQUESTED
  the donation;
- the **compiled HLO** carries ``input_output_alias={ {i}: (j, ...) }``
  pairs — proof XLA HONORED it.

Both counts must cover every leaf of the donated carry. AOT only
(``lower()`` + ``compile()`` on the CPU backend): nothing executes, so
the audit runs in device-free CI like the rest of graftaudit.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.analysis.core import Finding
from p2pnetwork_tpu.analysis.ir.registry import shape_class

__all__ = ["DonationAudit", "all_donation_audits", "check_aliasing",
           "audit_donation"]

#: ``input_output_alias={ {0}: (4, {}, may-alias), ... }`` — one
#: ``{output_path}: (param_index`` pair per honored alias.
_ALIAS_PAIR = re.compile(r"\{[\d,\s]*\}:\s*\(\d+")


@dataclasses.dataclass(frozen=True)
class DonationAudit:
    """One carry-donating program to verify. ``build()`` returns
    ``(jitted_fn, args, kwargs, n_carry_leaves)`` — the jitted donating
    variant, concrete example arguments (kwargs carry its static
    configuration), and how many array leaves of the carry must come
    back aliased."""

    name: str
    build: Callable[[], Tuple[Callable, tuple, dict, int]]
    doc: str = ""


def _flood_resume_state(g):
    """A mid-run FloodState whose leaves are DISTINCT buffers — fresh
    inits alias seen/frontier to one array, which the engine's
    ``_donatable`` gate deliberately routes around donation."""
    from p2pnetwork_tpu.models.flood import FloodState

    seed = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
    seed = seed & g.node_mask
    return FloodState(seen=seed | jnp.zeros_like(seed),
                      frontier=jnp.zeros_like(seed).at[1].set(True))


def _pushsum_resume_state(g):
    """A mid-run PushSumState (two distinct f32 leaves) for the
    run-to-convergence carry audit."""
    from p2pnetwork_tpu.models.pushsum import PushSumState

    n = g.n_nodes_padded
    return PushSumState(s=jnp.linspace(0.0, 1.0, n, dtype=jnp.float32),
                        w=jnp.ones(n, dtype=jnp.float32))


def all_donation_audits() -> List[DonationAudit]:
    """The engine's donating state-carry entry points, resolved through
    the engine's own ``donating_carry_loops()`` seam (sim/engine.py) —
    the exact jitted objects the resume paths dispatch, so a dropped
    ``donate_argnames`` on the real seam fails here, and a renamed or
    removed loop fails as unverifiable instead of silently ungating."""

    def run_from():
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        state = _flood_resume_state(g)
        args = (g, Flood(source=0), state, jax.random.key(0), 4)
        return engine.donating_carry_loops()["run_from"], args, {}, len(
            jax.tree_util.tree_leaves(state))

    def coverage_from():
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        state = _flood_resume_state(g)
        args = (g, Flood(source=0), state, jax.random.key(0))
        kwargs = {"coverage_target": 0.99, "max_rounds": 64}
        return (engine.donating_carry_loops()["coverage_from"], args,
                kwargs, len(jax.tree_util.tree_leaves(state)))

    def converged_from():
        from p2pnetwork_tpu.models import PushSum
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        state = _pushsum_resume_state(g)
        args = (g, PushSum(), state, jax.random.key(0))
        kwargs = {"stat": "variance", "threshold": 1e-6, "max_rounds": 64}
        return (engine.donating_carry_loops()["converged_from"], args,
                kwargs, len(jax.tree_util.tree_leaves(state)))

    def batch_from():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        proto = BatchFlood(method="auto")
        # init's admit scatters build every leaf as a distinct buffer,
        # so the fresh batch is already cleanly donatable (unlike the
        # single-message Flood init, whose seed IS both predicates).
        batch = proto.init(g, np.arange(32, dtype=np.int32) * 11 % 900)
        args = (g, proto, batch, jax.random.key(0))
        return (engine.donating_carry_loops()["batch_from"], args,
                {"max_rounds": 64},
                len(jax.tree_util.tree_leaves(batch)))

    def batch_from_repad():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.sim import engine
        from p2pnetwork_tpu.sim import graph as graph_mod

        g = shape_class("ws1k")
        proto = BatchFlood(method="auto")
        batch = proto.init(g, np.arange(32, dtype=np.int32) * 11 % 900)
        # Cross the pad boundary (graftchurn's live-growth path): the
        # zero-extended batch leaves are fresh concatenations, so the
        # grown-shape recompile must donate them exactly like the
        # originals — a repad that silently double-buffers would tax
        # every post-growth dispatch.
        g2 = graph_mod.grow(g, 200)
        assert g2.n_nodes_padded != g.n_nodes_padded
        batch = proto.repad(batch, g2.n_nodes_padded)
        args = (g2, proto, batch, jax.random.key(0))
        return (engine.donating_carry_loops()["batch_from"], args,
                {"max_rounds": 64},
                len(jax.tree_util.tree_leaves(batch)))

    def _query_batch(g):
        import numpy as np

        from p2pnetwork_tpu.models.querybatch import MinPlusQueries

        proto = MinPlusQueries(method="auto")
        return proto, proto.init(
            g, np.arange(8, dtype=np.int32) * 11 % 900,
            np.arange(8, dtype=np.int32) * 37 % 900)

    def query_from():
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        proto, qb = _query_batch(g)
        args = (g, proto, qb, jax.random.key(0))
        return (engine.donating_carry_loops()["query_from"], args,
                {"max_rounds": 64},
                len(jax.tree_util.tree_leaves(qb)))

    def query_from_rec():
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        proto, qb = _query_batch(g)
        args = (g, proto, qb, jax.random.key(0), _ring())
        return (engine.donating_carry_loops()["query_from_rec"], args,
                {"max_rounds": 64},
                len(jax.tree_util.tree_leaves(qb)) + 1)

    def _ring():
        from p2pnetwork_tpu.sim import flightrec

        return flightrec.FlightRecorder(capacity=64).init()

    def run_from_rec():
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        state = _flood_resume_state(g)
        args = (g, Flood(source=0), state, jax.random.key(0), 4, _ring())
        return engine.donating_carry_loops()["run_from_rec"], args, {}, (
            len(jax.tree_util.tree_leaves(state)) + 1)

    def coverage_from_rec():
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        state = _flood_resume_state(g)
        args = (g, Flood(source=0), state, jax.random.key(0), _ring())
        kwargs = {"coverage_target": 0.99, "max_rounds": 64}
        return (engine.donating_carry_loops()["coverage_from_rec"], args,
                kwargs, len(jax.tree_util.tree_leaves(state)) + 1)

    def batch_from_rec():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.sim import engine

        g = shape_class("ws1k")
        proto = BatchFlood(method="auto")
        batch = proto.init(g, np.arange(32, dtype=np.int32) * 11 % 900)
        args = (g, proto, batch, jax.random.key(0), _ring())
        return (engine.donating_carry_loops()["batch_from_rec"], args,
                {"max_rounds": 64},
                len(jax.tree_util.tree_leaves(batch)) + 1)

    def sharded_batch_from():
        import numpy as np

        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.parallel import sharded as SH

        g = shape_class("ws1k")
        mesh = M.ring_mesh(8)
        sg = SH.shard_graph(g, mesh)
        batch = BatchFlood().init(g, np.arange(32, dtype=np.int32) * 11 % 900)
        fn = SH._batch_cov_fn(mesh, SH.DEFAULT_AXIS, sg.n_shards, sg.block,
                              64, SH.DEFAULT_COMM, True)
        args = (sg.bkt_src, sg.bkt_dst, sg.bkt_mask,
                *SH._dyn_or_empty(sg), sg.node_mask, sg.out_degree,
                *SH._shard_batch_args(sg, batch))
        return fn, args, {}, 9  # the 9 MessageBatch carry leaves

    return [
        DonationAudit(
            name="engine/run_from", build=run_from,
            doc="fixed-rounds resume loop (engine.run_from)"),
        DonationAudit(
            name="sharded/batch_from", build=sharded_batch_from,
            doc="sharded batched message-plane ring loop "
                "(parallel/sharded.run_batch_until_coverage)"),
        DonationAudit(
            name="engine/coverage_from", build=coverage_from,
            doc="run-to-coverage resume loop "
                "(engine.run_until_coverage_from)"),
        DonationAudit(
            name="engine/converged_from", build=converged_from,
            doc="run-to-convergence resume loop "
                "(engine.run_until_converged)"),
        DonationAudit(
            name="engine/batch_from", build=batch_from,
            doc="batched message-plane loop "
                "(engine.run_batch_until_coverage)"),
        DonationAudit(
            name="engine/batch_from_repad", build=batch_from_repad,
            doc="batched message-plane loop after a live repad "
                "(graftchurn growth: graph.grow + protocol.repad)"),
        # The query plane's donating carry: f32 lane matrices are the
        # HBM-heavy leaves byte-budgeting exists for — a silently
        # double-buffered query carry would double exactly the cost
        # lane_budget gates.
        DonationAudit(
            name="engine/query_from", build=query_from,
            doc="batched query loop (engine.run_queries_until_done)"),
        DonationAudit(
            name="engine/query_from_rec", build=query_from_rec,
            doc="batched query loop with the flight-recorder ring "
                "(engine.run_queries_until_done(recorder=...))"),
        # The graftscope flight-recorder twins: the ring is one MORE
        # donated carry leaf — a recorder whose ring silently
        # double-buffers would tax every recorded run, so the alias is
        # audited like the state's.
        DonationAudit(
            name="engine/run_from_rec", build=run_from_rec,
            doc="fixed-rounds resume loop with the flight-recorder ring "
                "(engine.run_from(recorder=...))"),
        DonationAudit(
            name="engine/coverage_from_rec", build=coverage_from_rec,
            doc="run-to-coverage resume loop with the flight-recorder "
                "ring (engine.run_until_coverage_from(recorder=...))"),
        DonationAudit(
            name="engine/batch_from_rec", build=batch_from_rec,
            doc="batched message-plane loop with the flight-recorder "
                "ring (engine.run_batch_until_coverage(recorder=...))"),
    ]


def _alias_section(hlo: str) -> str:
    """The balanced-brace ``input_output_alias={...}`` section of the
    ENTRY line (alias paths contain nested ``{}``, so a lazy regex would
    stop at the first pair and under-count)."""
    i = hlo.find("input_output_alias=")
    if i < 0:
        return ""
    j = hlo.index("{", i)
    depth = 0
    for k in range(j, len(hlo)):
        if hlo[k] == "{":
            depth += 1
        elif hlo[k] == "}":
            depth -= 1
            if depth == 0:
                return hlo[j:k + 1]
    return hlo[j:]


def check_aliasing(fn, args, expected: int, kwargs=None) -> Dict[str, int]:
    """AOT-lower and compile ``fn(*args, **kwargs)``; count donation
    attributes in the MLIR (requested) and alias pairs in the compiled
    HLO (honored). Returns ``{"requested", "honored", "expected"}``."""
    kwargs = kwargs or {}
    lowered = fn.lower(*args, **kwargs) if hasattr(fn, "lower") \
        else jax.jit(fn).lower(*args, **kwargs)
    mlir = lowered.as_text()
    requested = mlir.count("tf.aliasing_output") \
        + mlir.count("jax.buffer_donor")
    hlo = lowered.compile().as_text()
    honored = len(_ALIAS_PAIR.findall(_alias_section(hlo)))
    return {"requested": requested, "honored": honored,
            "expected": expected}


def audit_donation(audits: Optional[List[DonationAudit]] = None
                   ) -> List[Finding]:
    """Verify every donating carry seam; one P0 finding per failure."""
    out: List[Finding] = []
    for audit in (audits if audits is not None else all_donation_audits()):
        try:
            fn, args, kwargs, expected = audit.build()
            counts = check_aliasing(fn, args, expected, kwargs)
        except Exception as e:  # noqa: BLE001 — failure IS the finding
            out.append(Finding(
                severity="P1", file=audit.name, line=0, col=0,
                rule="ir-donation-unverifiable",
                message=f"could not AOT-compile the carry step: "
                        f"{type(e).__name__}: {e}"))
            continue
        if counts["requested"] < expected:
            out.append(Finding(
                severity="P0", file=audit.name, line=0, col=0,
                rule="ir-donation-dropped",
                message=(f"jit requests donation for only "
                         f"{counts['requested']} of {expected} carry "
                         "leaves — donate_argnums/donate_argnames was "
                         "dropped or no longer covers the carry")))
        elif counts["honored"] < expected:
            out.append(Finding(
                severity="P0", file=audit.name, line=0, col=0,
                rule="ir-donation-dropped",
                message=(f"XLA aliased only {counts['honored']} of "
                         f"{expected} requested carry leaves — the "
                         "compiled input_output_alias dropped the "
                         "donation (shape/dtype/layout mismatch between "
                         "carry input and output?)")))
    return sorted(out)
