"""graftmem capacity planner: closed-form HBM extrapolation for the zoo.

The memory plane (memory.py) prices every lowering at the SMALL audit
shapes — deliberately, to keep the gate sub-minute. The serving campaign
asks a different question: does ``u32[W, N]`` at W=313 over a 1M-node
overlay fit a chip, and if not, how many shards? Answering it by
building the graph defeats the point of planning.

So: trace each registry entry at 2–3 scaled shape points (``ws256`` /
``ws512`` / ``ws1k`` — same generators, same seed, only the node count
moves; registry.zoo_at makes that a one-liner), price each point through
the same ``memory_analysis()`` + analytic-liveness machinery the ratchet
trusts, and fit per-entry closed-form coefficients::

    global_bytes(N_pad, E_pad, W) = c0 + cN·N_pad + cE·E_pad
                                       + cW·max(0, W - W0)·N_pad
    per_chip(shards)              = c0 + (global_bytes - c0) / shards

``cW`` (the lane-word slope) comes from a dedicated two-point probe of
the lane kernel at W=1 vs W=8 — the only coefficient the canonical
registry cannot expose, because every checked-in entry traces at one
word. ``W0`` is the word count the entry itself was traced at (1 for
the lane/batched entries, 0 otherwise), so the lane term prices only
the EXTRA words a wider deployment adds.

Identifiability caveat, stated rather than hidden: both graph families
grow edges linearly in nodes (WS: k·n, BA: m·n), so the fit points
cannot separate ``cN`` from ``cE`` — the least-squares solution splits
the joint slope at the family's edges-per-node ratio. Extrapolations
stay exact for targets built by the same generators (the planner derives
``E_pad`` from the family model for exactly this reason); feeding a
hand-rolled ``E_pad`` at a wildly different density is outside the
model's warranty, and ``plan()`` says so in its output.

The fitted coefficients ride in ``membudgets.json`` under
``capacity_model`` (written by ``graftaudit --write-membudgets``), so
``plan()`` extrapolates from checked-in, reviewed numbers WITHOUT
building or compiling anything — cheap enough for SimService to consult
on every submit/grow (the ``hbm_budget_bytes`` knob in serve/service.py
prices admission against :func:`serving_footprint_bytes` instead of
OOMing mid-tick).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["fit_capacity_model", "plan", "serving_footprint_bytes",
           "northstar_plan", "CAPACITY_SCHEMA", "DEFAULT_SERVING_ENTRY",
           "NODE_PAD_MULTIPLE", "LANES_PER_WORD"]

CAPACITY_SCHEMA = "graftmem-capacity-v1"
#: graph.from_edges' default node padding — the planner must pad target
#: node counts the way the builder will, or the extrapolation prices a
#: graph nobody constructs.
NODE_PAD_MULTIPLE = 128
EDGE_PAD_MULTIPLE = 128
#: One u32 lane word carries 32 concurrent messages (ops/bitset.py).
LANES_PER_WORD = 32
#: The serving plane's measured program: the batched run-to-coverage
#: engine loop — what one graftserve tick compiles down to.
DEFAULT_SERVING_ENTRY = "cov/batchflood-engine@ws"
#: Scaled shape points per family (suffixes onto ws/ba). Three points
#: over-determine the 2-dof family slope, so the fit residual is a real
#: linearity check, not zero by construction.
FIT_SIZES = ("256", "512", "1k")


def _round_up(x: int, m: int) -> int:
    return -(-int(x) // m) * m


def _base_name(name: str) -> str:
    """``cov/batchflood-engine@ws512`` -> ``cov/batchflood-engine@ws`` —
    one fitted model per (lowering, family), fed by every fit point."""
    head, _, cls = name.rpartition("@")
    fam = "ba" if cls.startswith("ba") else "ws"
    return f"{head}@{fam}"


def _lane_words_traced(name: str) -> int:
    """Words of lane state the registry entry itself carries (W0): the
    lane kernels and batched-flood loops trace at exactly one u32 word
    (32 lanes); everything else has no lane axis to widen."""
    return 1 if ("lanes" in name or "batchflood" in name) else 0


def _lstsq(rows: List[List[float]], ys: List[float]) -> List[float]:
    """Minimum-norm least squares (numpy lapack under the hood)."""
    import numpy as np

    a = np.asarray(rows, dtype=np.float64)  # graftlint: ignore[f64-literal] -- host-side fit numerics on Python floats, never a device array
    b = np.asarray(ys, dtype=np.float64)  # graftlint: ignore[f64-literal] -- same: lstsq conditioning wants f64, independent of the x64 flag
    sol, *_ = np.linalg.lstsq(a, b, rcond=None)
    return [float(v) for v in sol]


def _global_bytes(record: dict, shards: int = 1) -> Optional[float]:
    """The fit target of one memory record: whole-program resident bytes
    = shards × per-device compiled peak, plus the folded-constant payload
    (XLA embeds closure-captured graph tables in the executable — absent
    from every memory_analysis bucket, resident on chip all the same)."""
    comp = record.get("compiled")
    if comp is None:
        return None
    const = float(record.get("analytic", {}).get("const", 0))
    return int(shards) * float(comp.get("peak", 0)) + const


# ----------------------------------------------------------------- fitting


def _graph_dims(cls: str) -> Tuple[int, int]:
    """(N_pad, E_pad) of one shape-class — host-side numpy build, cheap
    at the ≤1k fit sizes, never touches a device."""
    from p2pnetwork_tpu.analysis.ir import registry

    g = registry.shape_class(cls)
    return int(g.n_nodes_padded), int(g.n_edges_padded)


def _lane_word_slope() -> dict:
    """cW: bytes per (extra lane word × padded node), probed by pricing
    the lane kernel at W=1 vs W=8 on ws256 — the one axis the canonical
    registry never widens."""
    import functools

    import jax.numpy as jnp

    from p2pnetwork_tpu.analysis.ir import memory, registry
    from p2pnetwork_tpu.ops import segment as S

    cls = "ws256"
    g = registry.shape_class(cls)
    n_pad = int(g.n_nodes_padded)
    got: Dict[int, float] = {}
    for w in (1, 8):
        def build(w=w):
            lanes = jnp.zeros((w, g.n_nodes_padded), dtype=jnp.uint32)
            return functools.partial(S.propagate_or_lanes, g,
                                     method="gather"), (lanes,)
        entry = registry.Lowering(
            name=f"_capfit/or_lanes-w{w}@{cls}", op="or_lanes",
            variant="gather", shape_class=cls, build=build, parity=False)
        rec = memory.collect_memory(
            [registry.trace_lowering(entry)]).get(entry.name, {})
        total = _global_bytes(rec)
        if total is not None:
            got[w] = total
    if len(got) < 2:
        return {"cW": 4.0, "basis": "fallback: u32 plane = 4·N bytes/word"}
    ws = sorted(got)
    cw = (got[ws[1]] - got[ws[0]]) / ((ws[1] - ws[0]) * n_pad)
    return {"cW": round(cw, 6),
            "basis": f"or_lanes/gather@{cls} W={ws[0]}->W={ws[1]}"}


def fit_capacity_model(canonical_records: Optional[dict] = None) -> dict:
    """Trace + price the zoo at every fit point and fit the per-entry
    closed forms. EXPENSIVE (two extra full-registry AOT passes plus the
    lane probe) — runs only under ``graftaudit --write-membudgets``.

    ``canonical_records`` (the ws1k/ba1k records the bless run already
    collected) supply the third fit point for free when given.
    """
    from p2pnetwork_tpu.analysis.ir import memory, registry

    import jax

    n_dev = len(jax.devices())
    # point label -> {"ws": cls, "ba": cls, records, dims per family}
    points: List[dict] = []
    for size in FIT_SIZES:
        ws_cls, ba_cls = f"ws{size}", f"ba{size}"
        zoo = registry.zoo_at(ws_cls, ba_cls)
        if size == "1k" and canonical_records is not None:
            records = canonical_records
        else:
            entries = [e for e in zoo if e.needs_devices <= n_dev]
            traces = [registry.trace_lowering(e) for e in entries]
            records = memory.collect_memory(traces)
        points.append({"ws": ws_cls, "ba": ba_cls, "records": records,
                       "shards": {e.name: e.needs_devices for e in zoo}})

    graph_info: Dict[str, dict] = {}
    for fam in ("ws", "ba"):
        dims = [_graph_dims(p[fam]) for p in points]
        slope = _lstsq([[1.0, float(n)] for n, _ in dims],  # graftlint: ignore[host-sync-in-loop] -- padded dims are plain Python ints
                       [float(e) for _, e in dims])  # graftlint: ignore[host-sync-in-loop] -- same
        graph_info[fam] = {
            "fit_classes": [p[fam] for p in points],
            "n_pad": [n for n, _ in dims],
            "e_pad": [e for _, e in dims],
            "e0": round(slope[0], 3),
            "edges_per_node": round(slope[1], 6),
        }

    # Group each entry's fit points by (lowering, family) base name.
    samples: Dict[str, List[Tuple[int, int, float]]] = {}
    shards_of: Dict[str, int] = {}
    for p in points:
        for name, rec in p["records"].items():
            shards = int(p["shards"].get(name, 1))  # graftlint: ignore[host-sync-in-loop] -- registry metadata, plain Python int
            total = _global_bytes(rec, shards)
            if total is None:
                continue
            base = _base_name(name)
            fam = base.rsplit("@", 1)[-1]
            n_pad, e_pad = _graph_dims(p[fam])
            samples.setdefault(base, []).append((n_pad, e_pad, total))
            shards_of[base] = shards

    fitted: Dict[str, dict] = {}
    for base, pts in sorted(samples.items()):
        if len(pts) < 2:
            continue  # one point fits nothing — entry stays unplannable
        rows = [[1.0, float(n), float(e)] for n, e, _ in pts]  # graftlint: ignore[host-sync-in-loop] -- fit points are host ints from the trace census
        ys = [y for _, _, y in pts]
        c0, cn, ce = _lstsq(rows, ys)
        resid = max(abs((c0 + cn * n + ce * e) - y) / max(y, 1.0)
                    for (n, e, y) in pts)
        fitted[base] = {
            "c0": round(c0, 3), "cN": round(cn, 6), "cE": round(ce, 6),
            "shards": shards_of.get(base, 1),
            "w0": _lane_words_traced(base),
            "points": len(pts),
            "max_resid": round(resid, 4),
        }

    return {
        "schema": CAPACITY_SCHEMA,
        "comment": ("Per-(lowering, family) closed-form HBM coefficients: "
                    "global_bytes = c0 + cN*N_pad + cE*E_pad + "
                    "cW*max(0, W-w0)*N_pad; per_chip(s) = c0 + "
                    "(global-c0)/s. Fit over the scaled shape points "
                    "(ws256/ws512/ws1k and ba siblings); cN/cE are "
                    "identified jointly through the family's "
                    "edges-per-node ratio (both generators grow edges "
                    "linearly in nodes). max_resid is the worst relative "
                    "fit error across the points — a linearity check."),
        "graph": graph_info,
        "lane": _lane_word_slope(),
        "entries": fitted,
    }


# ---------------------------------------------------------------- planning


def _load_model(model: Optional[dict]) -> Optional[dict]:
    if model is not None:
        return model
    from p2pnetwork_tpu.analysis.ir import memory

    return memory.load_membudgets().get("capacity_model")


def _eval_model(coeffs: dict, lane_cw: float, n_pad: int, e_pad: int,
                lane_words: int) -> Tuple[float, float]:
    """(global_bytes, shardable_bytes) of one fitted entry at a shape."""
    extra_w = max(0, int(lane_words) - int(coeffs.get("w0", 0)))
    shardable = (coeffs["cN"] * n_pad + coeffs["cE"] * e_pad
                 + lane_cw * extra_w * n_pad)
    return coeffs["c0"] + shardable, shardable


def plan(n_nodes: int, lanes: int = 0,
         entry: str = DEFAULT_SERVING_ENTRY,
         per_chip_hbm_bytes: float = 16 * 1024**3,
         headroom: float = 0.9,
         shard_candidates: Sequence[int] = (1, 2, 4, 8, 16, 32, 64, 128,
                                            256, 512, 1024),
         model: Optional[dict] = None) -> dict:
    """Extrapolate one lowering's HBM footprint to an arbitrary overlay
    WITHOUT building it, from the checked-in coefficients.

    Returns a plan document: padded dims, the modeled global footprint,
    a per-chip table over ``shard_candidates``, and the smallest shard
    count whose per-chip bytes fit under ``headroom × per_chip_hbm``
    (``recommended_shards``; None when nothing in the candidate list
    fits). Raises ``ValueError`` when membudgets.json carries no
    capacity model (run ``graftaudit --write-membudgets``) or the entry
    was never fitted."""
    m = _load_model(model)
    if not m or "entries" not in m:
        raise ValueError(
            "no capacity model: membudgets.json lacks `capacity_model` — "
            "bless one with `graftaudit --write-membudgets`")
    coeffs = m["entries"].get(entry)
    if coeffs is None:
        known = ", ".join(sorted(m["entries"]))
        raise ValueError(f"no fitted capacity entry {entry!r} "
                         f"(fitted: {known})")
    fam = entry.rsplit("@", 1)[-1]
    ginfo = m.get("graph", {}).get(fam, {})
    n_pad = _round_up(max(int(n_nodes), 1), NODE_PAD_MULTIPLE)
    e_est = (ginfo.get("edges_per_node", 0.0) * n_pad
             + ginfo.get("e0", 0.0))
    e_pad = _round_up(max(int(math.ceil(e_est)), 1), EDGE_PAD_MULTIPLE)
    lane_words = -(-int(lanes) // LANES_PER_WORD) if lanes else 0
    lane_cw = float(m.get("lane", {}).get("cW", 4.0))
    global_bytes, shardable = _eval_model(coeffs, lane_cw, n_pad, e_pad,
                                          lane_words)
    budget = headroom * float(per_chip_hbm_bytes)
    table = []
    recommended = None
    for s in shard_candidates:
        per_chip = coeffs["c0"] + shardable / max(int(s), 1)  # graftlint: ignore[host-sync-in-loop] -- shard counts are host ints, no device values in the planner
        fits = per_chip <= budget
        table.append({"shards": int(s), "per_chip_bytes": int(per_chip),  # graftlint: ignore[host-sync-in-loop] -- same
                      "fits": fits})
        if fits and recommended is None:
            recommended = int(s)  # graftlint: ignore[host-sync-in-loop] -- same
    return {
        "entry": entry,
        "n_nodes": int(n_nodes), "n_pad": n_pad, "e_pad": e_pad,
        "lanes": int(lanes), "lane_words": lane_words,
        "global_bytes": int(global_bytes),
        "per_chip_hbm_bytes": int(per_chip_hbm_bytes),
        "headroom": headroom,
        "recommended_shards": recommended,
        "per_chip": table,
        "model_note": ("E_pad derived from the family edges-per-node "
                       "model; densities far from the fitted generators "
                       "are outside the model's warranty"),
    }


def serving_footprint_bytes(n_padded: int, e_padded: int,
                            lane_words: int, shards: int = 1,
                            entry: str = DEFAULT_SERVING_ENTRY,
                            model: Optional[dict] = None) -> Optional[int]:
    """Per-chip planned bytes of the serving program over a CONCRETE
    graph (the caller already holds padded dims — SimService does) at
    ``lane_words`` of in-flight lane state. Returns None when no
    capacity model is checked in or the entry was never fitted — the
    caller degrades to not enforcing, loudly, rather than guessing."""
    m = _load_model(model)
    if not m:
        return None
    coeffs = (m.get("entries") or {}).get(entry)
    if coeffs is None:
        return None
    lane_cw = float(m.get("lane", {}).get("cW", 4.0))
    _, shardable = _eval_model(coeffs, lane_cw, int(n_padded),
                               int(e_padded), int(lane_words))
    return int(coeffs["c0"] + shardable / max(int(shards), 1))


def northstar_plan(per_chip_hbm_bytes: float = 16 * 1024**3,
                   model: Optional[dict] = None) -> dict:
    """ROADMAP item 2's SCALE question, answered from the checked-in
    coefficients: the 10k-lane (W=313 words) / 1M-node serving shape."""
    return plan(n_nodes=1_000_000, lanes=10_016,
                per_chip_hbm_bytes=per_chip_hbm_bytes, model=model)
