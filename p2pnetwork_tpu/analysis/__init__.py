"""graftlint: static analysis + runtime retrace budgets for this repo.

Two enforcement planes for the two disciplines the repo's performance
and liveness rest on:

- **Static** (stdlib ``ast``, no jax needed): JAX retrace/host-sync rules
  and concurrency lock-discipline rules over the source tree, with inline
  ``# graftlint: ignore[rule-id]`` suppressions and a checked-in
  ``baseline.json`` for grandfathered findings. CLI:
  ``python -m p2pnetwork_tpu.analysis p2pnetwork_tpu/`` (or the
  ``graftlint`` console script) — exit 0 means no new findings.

- **Runtime**: :class:`retrace_guard` asserts a per-block jit compile
  budget via the telemetry jaxhooks counters — the complement for
  retraces only visible with real shapes at runtime.

A third plane lives one layer down: **graftaudit**
(:mod:`p2pnetwork_tpu.analysis.ir`, the ``graftaudit`` CLI) audits what
the lowering zoo COMPILES to — jaxpr rules, signature parity, donation
aliasing, and the compiled-cost ratchet. It needs jax (CPU backend only)
and is therefore not imported here; this package stays importable in a
sockets-only environment.

And a fourth EXECUTES the thread plane: **graftrace**
(:mod:`p2pnetwork_tpu.analysis.race`, the ``graftrace`` CLI) explores
seeded deterministic schedules over the
:mod:`p2pnetwork_tpu.concurrency` seam with vector-clock happens-before
race detection — the dynamic verdict on what the static lock rules can
only conjecture. Not imported here either (it loads scenario modules);
its findings flow through this package's Finding/baseline machinery.

See GETTING_STARTED.md ("Static analysis & retrace budgets",
"IR audit & cost ratchet", "Deterministic concurrency testing") for the
rule tables and workflows.
"""

from p2pnetwork_tpu.analysis.core import (  # noqa: F401
    Finding,
    SEVERITIES,
    all_rules,
    analyze_paths,
    analyze_source,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    write_baseline,
)
from p2pnetwork_tpu.analysis.retrace_guard import (  # noqa: F401
    RetraceBudgetExceeded,
    retrace_guard,
)

__all__ = [
    "Finding", "SEVERITIES", "all_rules", "analyze_paths", "analyze_source",
    "apply_baseline", "default_baseline_path", "load_baseline",
    "write_baseline", "retrace_guard", "RetraceBudgetExceeded",
]
