"""graftlint core: findings, suppressions, baseline, and the file driver.

The repo's two hot halves fail in ways tests don't catch: the JAX sim
backend silently recompiles or host-syncs (throwing away the wins BENCH
measures), and the threaded/async sockets backend carries lock-using
modules whose deadlock and blocking-under-lock hazards only surface under
chaos load. Both are *compilation-discipline* and *lock-discipline*
properties — enforceable statically, per PR, from the AST alone.

This module is the rule-agnostic machinery:

- :class:`Finding` — one diagnostic: rule id, severity (P0 worst..P3),
  ``file:line:col``, message. Sorted worst-first, then by location.
- :class:`Module` — one parsed file handed to every rule: path, source,
  AST, import-alias tables (``jax``/``numpy`` however they were bound),
  and the per-line suppression table.
- Suppressions — ``# graftlint: ignore[RULE-A,RULE-B]`` on (or inside the
  statement starting at) the flagged line silences those rules there; a
  bare ``# graftlint: ignore`` silences every rule on that line. Keep a
  rationale in the same comment: suppressions are grep-able design notes.
- Baseline — ``baseline.json`` grandfathers pre-existing findings so the
  CLI can gate *new* ones from day one. Entries fingerprint on
  ``(rule, file, stripped source line)``, not line numbers, so unrelated
  edits above a finding don't churn the file; counts bound how many
  identical findings one fingerprint absorbs. Regenerate with
  ``python -m p2pnetwork_tpu.analysis --write-baseline`` after deliberate
  grandfathering; shrink it by fixing findings (the check fails if the
  baseline over-claims nothing — stale entries are pruned on rewrite).

Rules themselves live in :mod:`p2pnetwork_tpu.analysis.jaxrules` (retrace
and host-sync hazards) and :mod:`p2pnetwork_tpu.analysis.concurrency`
(lock discipline). Everything here is stdlib-only — the linter must run
in a sockets-only environment with no jax installed.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "Module", "Rule", "register_rule", "all_rules",
    "analyze_paths", "analyze_source", "load_baseline", "write_baseline",
    "apply_baseline", "default_baseline_path", "SEVERITIES",
]

#: Worst-first severity order. P0: will deadlock / retrace unboundedly.
#: P1: blocks or syncs on a hot path. P2: discipline drift that becomes a
#: P0/P1 under refactoring. P3: informational.
SEVERITIES = ("P0", "P1", "P2", "P3")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_,\- ]+)\])?")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One diagnostic. Field order defines sort order: severity first
    (P0 < P1 lexically, which is also worst-first), then location."""

    severity: str
    file: str
    line: int
    col: int
    rule: str
    message: str

    def fingerprint(self, source_line: str) -> Tuple[str, str, str]:
        """Line-number-free identity used by the baseline: the rule, the
        file, and the stripped source text of the flagged line."""
        return (self.rule, self.file, source_line.strip())

    def render(self) -> str:
        return (f"{self.file}:{self.line}:{self.col}: "
                f"{self.severity} [{self.rule}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file, pre-chewed for rules: AST, import aliases,
    suppression table, and a line accessor for baseline fingerprints."""

    def __init__(self, path: str, source: str, relpath: Optional[str] = None):
        self.path = path
        self.relpath = relpath if relpath is not None else path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # name the module was bound to -> canonical package, e.g. both
        # ``import numpy as np`` and ``from numpy import float64 as f64``
        # land in these tables so rules match usage, not spelling.
        self.aliases: Dict[str, str] = {}       # local name -> top package
        self.from_imports: Dict[str, str] = {}  # local name -> "pkg.attr"
        self._collect_imports()
        self.suppressions = self._collect_suppressions()

    # ------------------------------------------------------------ imports

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.aliases[local] = a.name.split(".")[0]
                    if a.asname and "." in a.name:
                        # ``import jax.numpy as jnp``: jnp -> jax.numpy
                        self.from_imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = f"{node.module}.{a.name}"
                    self.aliases.setdefault(local,
                                            node.module.split(".")[0])

    def imports_package(self, package: str) -> bool:
        return (package in self.aliases.values()
                or any(v == package or v.startswith(package + ".")
                       for v in self.from_imports.values()))

    def names_for(self, dotted: str) -> Set[str]:
        """Local names that resolve to ``dotted`` (e.g. ``jax.numpy`` ->
        {"jnp"}; ``numpy`` -> {"np", "numpy"})."""
        out = {local for local, full in self.from_imports.items()
               if full == dotted}
        out |= {local for local, pkg in self.aliases.items()
                if pkg == dotted and "." not in dotted
                and local not in self.from_imports}
        return out

    # ------------------------------------------------------- suppressions

    def _collect_suppressions(self) -> Dict[int, Optional[Set[str]]]:
        """1-based line -> set of suppressed rule ids, or ``None`` for all
        rules. Comments are read straight off the source lines (ast drops
        them); only lines actually containing the marker pay the regex.

        A marker covers the whole innermost *simple statement* containing
        it, so a comment on any continuation line of a multi-line call
        silences findings anchored at the statement's first line (and
        vice versa) — the documented "on or inside the flagged statement"
        contract. On a compound statement it covers the header lines
        only; a marker on a comment-only line between statements covers
        just that line (i.e. nothing) rather than the enclosing block."""
        markers: Dict[int, Optional[Set[str]]] = {}
        for i, text in enumerate(self.lines, start=1):
            if "graftlint" not in text:
                continue
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = m.group("rules")
            if rules is None:
                markers[i] = None
            elif markers.get(i, ()) is not None:
                # Merge rule ids; an existing bare ignore (None) already
                # suppresses everything and must not be narrowed.
                ids = {r.strip() for r in rules.split(",") if r.strip()}
                markers[i] = set(markers.get(i) or ()) | ids
        if not markers:
            return {}
        spans = []
        for s in ast.walk(self.tree):
            if not isinstance(s, ast.stmt):
                continue
            end = getattr(s, "end_lineno", None) or s.lineno
            body = getattr(s, "body", None)
            if isinstance(body, list) and body \
                    and isinstance(body[0], ast.stmt):
                # Compound statement (def/with/if/for/...): only its
                # HEADER lines count as "inside" it. A marker in the body
                # belongs to an inner statement — or, on a comment-only
                # line between statements, to nothing: matching the full
                # span would let one stray comment silence every finding
                # in the enclosing function.
                end = max(s.lineno, body[0].lineno - 1)
            spans.append((s.lineno, end))
        table: Dict[int, Optional[Set[str]]] = {}

        def merge(line: int, ids: Optional[Set[str]]) -> None:
            if ids is None:
                table[line] = None
            elif table.get(line, ()) is not None:
                table[line] = set(table.get(line) or ()) | ids

        for line, ids in markers.items():
            best = None
            for lo, hi in spans:
                if lo <= line <= hi and (
                        best is None or hi - lo < best[1] - best[0]):
                    best = (lo, hi)
            lo, hi = best if best is not None else (line, line)
            for covered in range(lo, hi + 1):
                merge(covered, ids)
        return table

    def suppressed(self, finding: Finding) -> bool:
        allowed = self.suppressions.get(finding.line, ())
        if allowed is None:
            return True
        return finding.rule in allowed

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


@dataclasses.dataclass(frozen=True)
class Rule:
    """A registered check: ``run(module)`` yields Findings (severity and
    id are stamped here so rule bodies only supply location + message)."""

    id: str
    severity: str
    doc: str
    run: Callable[[Module], Iterable[Finding]]


_RULES: Dict[str, Rule] = {}  # graftlint: ignore[unbounded-cache] -- rule registry: one entry per @register_rule decorator at import time, fixed vocabulary


def register_rule(id: str, severity: str, doc: str):
    """Decorator for rule functions ``fn(module) -> iterable of (node,
    message)``; wraps them to emit stamped :class:`Finding` records."""
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}")

    def deco(fn):
        def run(module: Module):
            for node, message in fn(module):
                yield Finding(severity=severity, file=module.relpath,
                              line=getattr(node, "lineno", 0),
                              col=getattr(node, "col_offset", 0),
                              rule=id, message=message)
        if id in _RULES:
            raise ValueError(f"duplicate rule id {id!r}")
        _RULES[id] = Rule(id=id, severity=severity, doc=doc, run=run)
        return fn
    return deco


def all_rules() -> Dict[str, Rule]:
    # Importing the rule modules registers them; deferred so core stays
    # importable mid-bootstrap (the rule modules import this one).
    from p2pnetwork_tpu.analysis import concurrency, jaxrules  # noqa: F401
    return dict(_RULES)


# ---------------------------------------------------------------- driver

def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if not os.path.exists(p):
            # A typo'd target must not analyze zero files and report
            # "clean" — that permanently disables the gate with a green
            # check. The CLI maps this to exit 2.
            raise FileNotFoundError(f"no such file or directory: {p}")
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".") and
                             d not in ("__pycache__", "bench_cache"))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Dict[str, Rule]] = None,
                   respect_suppressions: bool = True) -> List[Finding]:
    """Run every rule over one source string (the test-fixture entry)."""
    module = Module(path, source)
    return _run_rules(module, rules if rules is not None else all_rules(),
                      respect_suppressions)


def _run_rules(module: Module, rules: Dict[str, Rule],
               respect_suppressions: bool) -> List[Finding]:
    out: List[Finding] = []
    for rule in rules.values():
        for finding in rule.run(module):
            if respect_suppressions and module.suppressed(finding):
                continue
            out.append(finding)
    return sorted(out)


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Dict[str, Rule]] = None,
                  root: Optional[str] = None,
                  respect_suppressions: bool = True,
                  collect_sources: Optional[Dict[str, Module]] = None,
                  ) -> List[Finding]:
    """Run every rule over every ``.py`` file under ``paths``.

    ``root`` makes reported file paths relative (baseline entries must not
    bake in an absolute checkout path). A file that fails to parse yields
    a single P1 ``parse-error`` finding instead of killing the run — a
    linter that dies on one bad file gates nothing.
    """
    if rules is None:
        rules = all_rules()
    root = os.path.abspath(root) if root else os.getcwd()
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            module = Module(path, source, relpath=rel)
        except (SyntaxError, ValueError, UnicodeDecodeError, OSError) as e:
            # ValueError covers ast.parse on NUL bytes — the contract is
            # "unanalyzable file = one P1 finding", never a dead run.
            findings.append(Finding(
                severity="P1", file=rel, line=getattr(e, "lineno", 0) or 0,
                col=0, rule="parse-error",
                message=f"could not analyze: {type(e).__name__}: {e}"))
            continue
        if collect_sources is not None:
            collect_sources[rel] = module
        findings.extend(_run_rules(module, rules, respect_suppressions))
    return sorted(findings)


# --------------------------------------------------------------- baseline

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[Tuple[str, str, str], int]:
    """``{(rule, file, stripped line): allowed count}``. A missing file is
    an empty baseline — the clean-tree state needs no artifact."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str], int] = {}
    for entry in data.get("findings", ()):
        key = (entry["rule"], entry["file"], entry["code"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def write_baseline(findings: Sequence[Finding],
                   modules: Dict[str, Module],
                   path: Optional[str] = None,
                   keep: Optional[Dict[Tuple[str, str, str], int]] = None,
                   ) -> str:
    """Grandfather ``findings`` (typically the current run's full output):
    collapse to fingerprint counts and write the JSON artifact. ``keep``
    carries prior entries to preserve verbatim (the CLI passes entries for
    files a path-subset run did not analyze, so such a run cannot
    silently drop other files' grandfathered findings)."""
    path = path or default_baseline_path()
    counts: Dict[Tuple[str, str, str], int] = dict(keep or {})
    for f in findings:
        module = modules.get(f.file)
        code = module.line_text(f.line) if module else ""
        key = f.fingerprint(code)
        counts[key] = counts.get(key, 0) + 1
    entries = [{"rule": rule, "file": file, "code": code, "count": n}
               for (rule, file, code), n in sorted(counts.items())]
    payload = {
        "comment": ("graftlint grandfathered findings. Entries match on "
                    "(rule, file, stripped source line) — line-number "
                    "drift does not churn this file. Shrink it by fixing "
                    "findings; regenerate with --write-baseline."),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")
    return path


def apply_baseline(findings: Sequence[Finding],
                   modules: Dict[str, Module],
                   baseline: Dict[Tuple[str, str, str], int],
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, grandfathered). Each baseline fingerprint absorbs
    at most its recorded count — a *new* duplicate of an old finding on
    the same line still fails the gate."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        module = modules.get(f.file)
        code = module.line_text(f.line) if module else ""
        key = f.fingerprint(code)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
