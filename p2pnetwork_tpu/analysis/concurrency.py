"""graftlint concurrency rules: lock discipline for the sockets backend.

The threaded/async half of the repo (node event loops, phi monitoring
threads, chaos driver threads, telemetry scrapers) carries lock-using
modules whose hazards only surface under chaos load — the wrong
interleaving of a blocking call under a held lock, or two locks taken in
opposite orders on two threads. These are *graph* properties of the code,
checkable statically:

The analysis builds, per module, a lock-acquisition model:

- **lock inventory** — ``self.x = threading.Lock()/RLock()/Condition()``
  assignments name class locks ``Class.x``; module-level assignments name
  module locks. ``with`` expressions that resolve to neither but *look*
  like locks (dotted text containing "lock"/"mutex"/"cond") become opaque
  locks: they participate in ordering but not in guard analysis.
- **regions** — ``with <lock>:`` blocks, nested, per function, including
  what is called, read, written, awaited and blocked-on inside each.
- **call edges** — ``self.method()`` and module-function calls resolve
  within the module; a bounded fixpoint propagates "locks this call may
  acquire" and "this call may block" through the edges, so a blocking
  call two frames below a ``with`` still indicts the ``with``.

Rules (see each docstring): ``lock-order-cycle`` (P0),
``lock-across-await`` (P0), ``blocking-under-lock`` (P1),
``async-blocking-call`` (P1), ``lock-guard`` (P2, inconsistent guard
discipline — the read that is safe today and a torn read after the next
refactor), ``lock-open-call`` (P2, calling out to foreign code while
holding a lock — the classic deadlock ingredient), ``wait-untimed`` (P2,
unbounded cross-thread waits).

Heuristics are deliberately conservative-but-syntactic; the suppression
and baseline machinery (core.py) absorbs judged-acceptable sites, each
with its rationale in the comment.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from p2pnetwork_tpu.analysis.core import Module, register_rule
from p2pnetwork_tpu.analysis.jaxrules import dotted_name, resolve_dotted

_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    # The concurrency seam's factories (p2pnetwork_tpu/concurrency.py):
    # production code constructs locks through these, and the inventory
    # must keep recognizing them or every guard/ordering rule silently
    # degrades to the "lockish word" heuristic.
    "p2pnetwork_tpu.concurrency.lock": "Lock",
    "p2pnetwork_tpu.concurrency.rlock": "RLock",
    "p2pnetwork_tpu.concurrency.condition": "Condition",
}
_LOCKISH_WORDS = ("lock", "mutex", "cond")

#: Attribute methods that mutate a container in place — used both to
#: classify guarded-state writes and to exempt them from lock-open-call.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "setdefault",
    "difference_update", "intersection_update", "symmetric_difference_update",
    "sort", "reverse", "record",
})
_SAFE_ATTR_CALLS = _MUTATORS | frozenset({
    "get", "items", "keys", "values", "copy", "count", "index", "union",
    "difference", "intersection", "issubset", "issuperset", "most_common",
    "split", "rsplit", "strip", "lstrip", "rstrip", "startswith", "endswith",
    "encode", "decode", "format", "lower", "upper", "replace", "partition",
    "rpartition", "hexdigest", "digest", "labels", "snapshot",
})
_SAFE_BUILTINS = frozenset({
    "len", "list", "dict", "set", "tuple", "frozenset", "str", "int",
    "float", "bool", "bytes", "sorted", "reversed", "min", "max", "sum",
    "abs", "round", "any", "all", "zip", "enumerate", "range", "map",
    "filter", "isinstance", "issubclass", "getattr", "hasattr", "setattr",
    "repr", "format", "id", "hash", "iter", "next", "type", "vars",
    "super", "ValueError", "TypeError", "KeyError", "RuntimeError",
})
_SOCKET_BLOCKING_ATTRS = frozenset({"recv", "recvfrom", "recv_into",
                                    "sendall", "accept"})
_SUBPROCESS_BLOCKING = frozenset({"subprocess.run", "subprocess.call",
                                  "subprocess.check_call",
                                  "subprocess.check_output"})


def _blocking_desc(module: Module, call: ast.Call) -> Optional[str]:
    """A human-readable description if ``call`` is a known blocking op."""
    fn = call.func
    resolved = resolve_dotted(module, fn)
    if resolved == "time.sleep":
        return "time.sleep()"
    if resolved == "p2pnetwork_tpu.concurrency.sleep":
        # The seam's sleep is time.sleep in production (a scheduling
        # point only under graftrace) — same blocking verdict.
        return "concurrency.sleep()"
    if resolved in _SUBPROCESS_BLOCKING:
        return f"{resolved}()"
    if resolved is not None and resolved.startswith("requests."):
        return f"{resolved}() (network I/O)"
    if isinstance(fn, ast.Name) and fn.id == "input":
        return "input()"
    if not isinstance(fn, ast.Attribute):
        return None
    untimed = not call.args and not call.keywords
    if fn.attr in _SOCKET_BLOCKING_ATTRS:
        return f"socket .{fn.attr}()"
    if fn.attr == "wait" and untimed:
        return "untimed .wait()"
    if fn.attr == "result" and untimed:
        return "untimed .result()"
    if fn.attr == "join" and untimed:
        return "untimed .join()"
    if fn.attr in ("get", "put"):
        receiver = (dotted_name(fn.value) or "").lower()
        if "queue" in receiver and not any(
                kw.arg in ("timeout", "block") for kw in call.keywords):
            return f"untimed queue .{fn.attr}()"
    return None


# -------------------------------------------------------------- summaries


@dataclasses.dataclass
class _Summary:
    key: str
    name: str
    class_name: Optional[str]
    node: ast.AST
    is_async: bool
    # (lock id, with-node) for every direct acquisition.
    acquires: List[Tuple[str, ast.AST]] = dataclasses.field(
        default_factory=list)
    # Syntactic nesting: (outer lock, inner lock) -> sample site.
    nest_edges: Dict[Tuple[str, str], ast.AST] = dataclasses.field(
        default_factory=dict)
    # Every resolvable call: (held locks, site, callee key, in await).
    calls: List[Tuple[FrozenSet[str], ast.AST, str, bool]] = \
        dataclasses.field(default_factory=list)
    # Unresolvable calls made while ≥1 lock is held.
    opaque_under: List[Tuple[FrozenSet[str], ast.AST, str]] = \
        dataclasses.field(default_factory=list)
    # Known-blocking ops: (held locks, site, description, in await).
    blocking: List[Tuple[FrozenSet[str], ast.AST, str, bool]] = \
        dataclasses.field(default_factory=list)
    awaits_under: List[Tuple[FrozenSet[str], ast.AST]] = dataclasses.field(
        default_factory=list)
    # self-attribute traffic: (attr, site, held locks, is mutation).
    attr_access: List[Tuple[str, ast.AST, FrozenSet[str], bool]] = \
        dataclasses.field(default_factory=list)
    # module-global traffic: (name, site, held locks, is mutation).
    global_access: List[Tuple[str, ast.AST, FrozenSet[str], bool]] = \
        dataclasses.field(default_factory=list)
    # Fixpoint results.
    acquires_closure: Set[str] = dataclasses.field(default_factory=set)
    may_block: Optional[str] = None


class _ModuleConcurrency:
    """One module's lock model: inventory, per-function summaries, edges."""

    def __init__(self, module: Module):
        self.module = module
        self.class_locks: Dict[str, Dict[str, str]] = {}   # class -> attr -> kind
        self.module_locks: Dict[str, str] = {}             # name -> kind
        self.lock_kinds: Dict[str, str] = {}               # lock id -> kind
        self.summaries: Dict[str, _Summary] = {}
        self.module_globals: Set[str] = set()
        self._collect_inventory()
        self._collect_summaries()
        self._fixpoint()

    # ---------------------------------------------------------- inventory

    def _lock_kind(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            return _LOCK_FACTORIES.get(
                resolve_dotted(self.module, value.func) or "")
        return None

    def _collect_inventory(self) -> None:
        tree = self.module.tree
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign):
                kind = self._lock_kind(stmt.value)
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_globals.add(tgt.id)
                        if kind:
                            self.module_locks[tgt.id] = kind
                            self.lock_kinds[tgt.id] = kind
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks: Dict[str, str] = {}
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign):
                    kind = self._lock_kind(node.value)
                    if not kind:
                        continue
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            locks[tgt.attr] = kind
                            self.lock_kinds[f"{cls.name}.{tgt.attr}"] = kind
            if locks:
                self.class_locks[cls.name] = locks

    def _resolve_lock(self, expr: ast.AST,
                      class_name: Optional[str]) -> Optional[str]:
        """Lock id for a with-expression, or None if it isn't lock-like.
        ``self.x`` resolves against the enclosing class's inventory; a
        bare name against module locks; anything whose dotted text smells
        like a lock becomes an opaque lock id."""
        dotted = dotted_name(expr)
        if dotted is None:
            return None
        if dotted in self.module_locks:
            return dotted
        if (class_name and dotted.startswith("self.")
                and dotted[5:] in self.class_locks.get(class_name, {})):
            return f"{class_name}.{dotted[5:]}"
        low = dotted.lower()
        if any(w in low for w in _LOCKISH_WORDS):
            self.lock_kinds.setdefault(dotted, "opaque")
            return dotted
        return None

    # ---------------------------------------------------------- summaries

    def _collect_summaries(self) -> None:
        tree = self.module.tree
        targets: List[Tuple[ast.AST, Optional[str], str]] = []
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                targets.append((stmt, None, ""))
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        targets.append((sub, stmt.name, f"{stmt.name}."))
        # Keys are pre-registered so a method can resolve calls to methods
        # defined after it (summaries fill in as each body is walked).
        self.function_keys: Set[str] = {
            prefix + fn.name for fn, _, prefix in targets}
        for fn, class_name, prefix in targets:
            self._summarize(fn, class_name=class_name, prefix=prefix)

    def _summarize(self, fn, class_name: Optional[str], prefix: str) -> None:
        key = prefix + fn.name
        summary = _Summary(
            key=key, name=fn.name, class_name=class_name, node=fn,
            is_async=isinstance(fn, ast.AsyncFunctionDef))
        self.summaries[key] = summary
        declared_globals: Set[str] = set()
        # Locals whose value derives from a self attribute — method calls
        # on them under a lock are treated as touching that guarded state,
        # not as calling out to foreign code.
        derived: Dict[str, str] = {}
        local_defs: Dict[str, ast.AST] = {}

        def root_attr(expr: ast.AST) -> Optional[str]:
            """The self-attribute (or derived local's attribute) a value
            expression is rooted at, if any."""
            node = expr
            while True:
                if isinstance(node, ast.Call):
                    node = node.func
                elif isinstance(node, ast.Attribute):
                    if (isinstance(node.value, ast.Name)
                            and node.value.id == "self"):
                        return node.attr
                    node = node.value
                elif isinstance(node, ast.Subscript):
                    node = node.value
                elif isinstance(node, ast.Name):
                    return derived.get(node.id)
                else:
                    return None

        def record_attr(attr: str, site: ast.AST, held: FrozenSet[str],
                        mutation: bool) -> None:
            summary.attr_access.append((attr, site, held, mutation))

        def visit(node: ast.AST, held: Tuple[str, ...],
                  in_await: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not fn:
                    # Nested defs execute later, not under these locks;
                    # summarize independently and resolve calls by name.
                    local_defs[node.name] = node
                    self._summarize(node, class_name, prefix=key + ".")
                    return
                for child in ast.iter_child_nodes(node):
                    visit(child, held, in_await)
                return
            if isinstance(node, ast.Lambda):
                return  # a value, not an execution under these locks
            if isinstance(node, ast.Global):
                declared_globals.update(node.names)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in node.items:
                    lock = self._resolve_lock(item.context_expr, class_name)
                    if lock is not None:
                        summary.acquires.append((lock, node))
                        for outer in held:
                            summary.nest_edges.setdefault((outer, lock),
                                                          node)
                        acquired.append(lock)
                    else:
                        visit(item.context_expr, held, in_await)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held, in_await)
                inner = held + tuple(acquired)
                for child in node.body:
                    visit(child, inner, in_await)
                return
            if isinstance(node, ast.Await):
                if held:
                    summary.awaits_under.append((frozenset(held), node))
                visit(node.value, held, True)
                return
            if isinstance(node, ast.Assign):
                rooted = root_attr(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if rooted is not None:
                            derived[tgt.id] = rooted
                        if tgt.id in declared_globals:
                            summary.global_access.append(
                                (tgt.id, node, frozenset(held), True))
                visit(node.value, held, in_await)
                for tgt in node.targets:
                    visit(tgt, held, in_await)
                return
            if isinstance(node, ast.Call):
                self._record_call(summary, node, held, in_await,
                                  class_name, derived, local_defs, key,
                                  record_attr)
                for child in ast.iter_child_nodes(node):
                    visit(child, held, in_await)
                return
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) and \
                        node.value.id == "self":
                    mutation = isinstance(node.ctx, (ast.Store, ast.Del))
                    record_attr(node.attr, node, frozenset(held), mutation)
                visit(node.value, held, in_await)
                return
            if isinstance(node, ast.Subscript):
                # self.x[...] = v mutates the container behind self.x.
                rooted = root_attr(node.value)
                if rooted is not None and isinstance(node.ctx,
                                                     (ast.Store, ast.Del)):
                    record_attr(rooted, node, frozenset(held), True)
                for child in ast.iter_child_nodes(node):
                    visit(child, held, in_await)
                return
            if isinstance(node, ast.Name):
                if (node.id in self.module_globals
                        and node.id not in self.module_locks):
                    mutation = (isinstance(node.ctx, (ast.Store, ast.Del))
                                and node.id in declared_globals)
                    if mutation or isinstance(node.ctx, ast.Load):
                        summary.global_access.append(
                            (node.id, node, frozenset(held), mutation))
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held, in_await)

        visit(fn, (), False)

    def _record_call(self, summary: _Summary, call: ast.Call,
                     held: Tuple[str, ...], in_await: bool,
                     class_name: Optional[str], derived: Dict[str, str],
                     local_defs: Dict[str, ast.AST], key: str,
                     record_attr) -> None:
        held_fs = frozenset(held)
        fn = call.func
        desc = _blocking_desc(self.module, call)
        if desc is not None:
            summary.blocking.append((held_fs, call, desc, in_await))
            return
        # Resolvable callees: self.method, module function, nested def.
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name) and fn.value.id == "self"
                and class_name is not None
                and f"{class_name}.{fn.attr}" in self.function_keys):
            summary.calls.append(
                (held_fs, call, f"{class_name}.{fn.attr}", in_await))
            return
        if isinstance(fn, ast.Name):
            if fn.id in local_defs:
                summary.calls.append((held_fs, call, f"{key}.{fn.id}",
                                      in_await))
                return
            if fn.id in self.function_keys:
                summary.calls.append((held_fs, call, fn.id, in_await))
                return
            if fn.id in self._module_classes():
                # Local class construction: follow __init__ when defined
                # (a missing __init__ is object's — trivially safe).
                init = f"{fn.id}.__init__"
                if init in self.function_keys:
                    summary.calls.append((held_fs, call, init, in_await))
                return
            if fn.id in _SAFE_BUILTINS:
                return
        if not held:
            return
        # Under a lock and unresolvable: either touching guarded state
        # (fine) or calling out to foreign code (the open-call hazard).
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SAFE_ATTR_CALLS:
                root = self._receiver_root(fn.value, derived)
                if root is not None:
                    if root != "<foreign>":
                        record_attr(root, call, held_fs,
                                    fn.attr in _MUTATORS)
                    return
                return  # container-style call on a local value
            root = self._receiver_root(fn.value, derived)
            if root is not None and root != "<foreign>":
                # Method call on guarded/derived self state with a
                # non-container method name: still a call out of our
                # control only if the receiver is a foreign object; a
                # self-attribute holding plain data gets the benefit of
                # the doubt only for container methods above, so flag it.
                # Name the receiver the code actually calls: for a
                # derived local (`mine = self._crdts.get(..)`), claiming
                # `self._crdts.merge()` would point at a method the
                # container doesn't have.
                if isinstance(fn.value, ast.Name) and fn.value.id in derived:
                    label = (f"{fn.value.id}.{fn.attr}() (on `{fn.value.id}`,"
                             f" derived from self.{root})")
                else:
                    label = f"self.{root}.{fn.attr}()"
                summary.opaque_under.append((held_fs, call, label))
                return
            summary.opaque_under.append(
                (held_fs, call, f"{dotted_name(fn) or fn.attr}()"))
            return
        label = dotted_name(fn) or getattr(fn, "id", None) or "<expr>"
        summary.opaque_under.append((held_fs, call, f"{label}()"))

    def _receiver_root(self, expr: ast.AST,
                       derived: Dict[str, str]) -> Optional[str]:
        """self-attribute name a receiver is rooted at; ``None`` for plain
        locals/literals; ``"<foreign>"`` for anything rooted elsewhere."""
        node = expr
        while True:
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) and \
                        node.value.id == "self":
                    return node.attr
                node = node.value
            elif isinstance(node, ast.Subscript):
                node = node.value
            elif isinstance(node, ast.Call):
                node = node.func
            elif isinstance(node, ast.Name):
                root = derived.get(node.id)
                return root  # a derived local maps home; else plain local
            else:
                return None

    def _module_classes(self) -> Set[str]:
        return set(self.class_locks) | {
            n.name for n in self.module.tree.body
            if isinstance(n, ast.ClassDef)}

    # ----------------------------------------------------------- fixpoint

    def _fixpoint(self) -> None:
        for s in self.summaries.values():
            s.acquires_closure = {lock for lock, _ in s.acquires}
            direct = [d for _, _, d, _ in s.blocking]
            s.may_block = direct[0] if direct else None
        for _ in range(12):
            changed = False
            for s in self.summaries.values():
                for _, _, callee_key, _ in s.calls:
                    callee = self.summaries.get(callee_key)
                    if callee is None:
                        continue
                    before = len(s.acquires_closure)
                    s.acquires_closure |= callee.acquires_closure
                    if len(s.acquires_closure) != before:
                        changed = True
                    if s.may_block is None and callee.may_block is not None:
                        s.may_block = (f"{callee.name}() -> "
                                       f"{callee.may_block}")
                        changed = True
            if not changed:
                break

    # -------------------------------------------------------------- edges

    def lock_edges(self) -> Dict[Tuple[str, str], Tuple[ast.AST, str]]:
        """(outer, inner) -> (site, via) for every ordered pair where
        ``inner`` may be acquired while ``outer`` is held — syntactic
        nesting plus call-closure edges."""
        edges: Dict[Tuple[str, str], Tuple[ast.AST, str]] = {}
        for s in self.summaries.values():
            for pair, site in s.nest_edges.items():
                edges.setdefault(pair, (site, s.key))
            for held, site, callee_key, _ in s.calls:
                callee = self.summaries.get(callee_key)
                if callee is None or not held:
                    continue
                for inner in callee.acquires_closure:
                    for outer in held:
                        edges.setdefault(
                            (outer, inner),
                            (site, f"{s.key} -> {callee_key}"))
        return edges


def _concurrency(module: Module) -> _ModuleConcurrency:
    cached = getattr(module, "_graftlint_concurrency", None)
    if cached is None:
        cached = _ModuleConcurrency(module)
        module._graftlint_concurrency = cached
    return cached


def _fmt_locks(locks: Iterable[str]) -> str:
    return "/".join(sorted(locks))


# ------------------------------------------------------------------ rules


@register_rule(
    "lock-order-cycle", "P0",
    "Two (or more) locks are acquired in conflicting orders — or a "
    "non-reentrant lock is re-acquired while held. The wrong two threads "
    "deadlock forever.")
def rule_lock_order_cycle(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    edges = conc.lock_edges()
    # Self-deadlock: re-acquiring a plain Lock (RLock/Condition re-enter).
    for (outer, inner), (site, via) in sorted(edges.items()):
        if outer == inner and conc.lock_kinds.get(outer) == "Lock":
            yield site, (f"non-reentrant lock `{outer}` may be re-acquired "
                         f"while already held (via {via}) — guaranteed "
                         "self-deadlock on that path")
    # Order cycles across distinct locks.
    graph: Dict[str, Set[str]] = {}
    for (outer, inner) in edges:
        if outer != inner:
            graph.setdefault(outer, set()).add(inner)
    reported: Set[FrozenSet[str]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 1:
                    cycle = frozenset(path)
                    if cycle in reported:
                        continue
                    reported.add(cycle)
                    site, via = edges[(path[-1], start)]
                    chain = " -> ".join(path + [start])
                    yield site, (f"lock-order cycle {chain} (edge via "
                                 f"{via}) — two threads entering from "
                                 "different ends deadlock")
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))


@register_rule(
    "lock-across-await", "P0",
    "A threading lock is held across an `await`: the coroutine parks with "
    "the lock held, and any thread contending for it blocks the whole "
    "event loop with it.")
def rule_lock_across_await(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    for s in conc.summaries.values():
        for held, site in s.awaits_under:
            yield site, (f"`await` while holding {_fmt_locks(held)} — "
                         "release before suspending (copy what you need "
                         "under the lock, await after), or use an asyncio "
                         "lock confined to the loop")


@register_rule(
    "blocking-under-lock", "P1",
    "A known-blocking call (sleep, socket op, untimed wait/result/join, "
    "untimed queue get/put, subprocess) runs while a lock is held — every "
    "other thread needing that lock stalls for the duration.")
def rule_blocking_under_lock(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    for s in conc.summaries.values():
        for held, site, desc, _ in s.blocking:
            if held:
                yield site, (f"{desc} while holding {_fmt_locks(held)} — "
                             "move the blocking work outside the critical "
                             "section")
        for held, site, callee_key, _ in s.calls:
            callee = conc.summaries.get(callee_key)
            if held and callee is not None and callee.may_block:
                yield site, (f"call to {callee.name}() while holding "
                             f"{_fmt_locks(held)} may block "
                             f"({callee.may_block}) — move it outside the "
                             "critical section")


@register_rule(
    "async-blocking-call", "P1",
    "A blocking call inside `async def` (not awaited): it stalls the "
    "whole event loop — every connection this node serves.")
def rule_async_blocking(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    for s in conc.summaries.values():
        if not s.is_async:
            continue
        for _, site, desc, in_await in s.blocking:
            if in_await:
                continue  # `await x.wait()` — the asyncio form, fine
            yield site, (f"{desc} inside `async def {s.name}` — use the "
                         "asyncio equivalent (asyncio.sleep, run_in_"
                         "executor, wait_for) or move it off the loop")
        for _, site, callee_key, in_await in s.calls:
            callee = conc.summaries.get(callee_key)
            if (not in_await and callee is not None and callee.may_block
                    and not callee.is_async):
                yield site, (f"call to {callee.name}() inside `async def "
                             f"{s.name}` may block the event loop "
                             f"({callee.may_block})")


@register_rule(
    "lock-guard", "P2",
    "State is written under a lock in one place and touched without it in "
    "another: the unguarded access is a torn read/write waiting for the "
    "next refactor (or the next chaos run) to expose it.")
def rule_lock_guard(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    # ---- class attributes -------------------------------------------
    by_class: Dict[str, List[Tuple[str, ast.AST, FrozenSet[str], bool, str]]]
    by_class = {}
    for s in conc.summaries.values():
        if s.class_name is None or s.class_name not in conc.class_locks:
            continue
        skip = s.name in ("__init__", "__new__", "__del__") or \
            s.name.endswith("_locked")
        if skip:
            continue
        for attr, site, held, mutation in s.attr_access:
            by_class.setdefault(s.class_name, []).append(
                (attr, site, held, mutation, s.name))
    for cls, accesses in sorted(by_class.items()):
        inventory = {f"{cls}.{a}" for a in conc.class_locks[cls]}
        lock_attrs = set(conc.class_locks[cls])
        guards: Dict[str, Set[str]] = {}
        for attr, _, held, mutation, _ in accesses:
            if mutation and attr not in lock_attrs:
                locks = set(held) & inventory
                if locks:
                    guards.setdefault(attr, set()).update(locks)
        for attr, site, held, mutation, fn_name in accesses:
            guard = guards.get(attr)
            if not guard or set(held) & guard:
                continue
            verb = "written" if mutation else "read"
            yield site, (f"self.{attr} is {verb} in {fn_name}() without "
                         f"{_fmt_locks(guard)}, which guards its writes "
                         "elsewhere — take the lock (or document the race "
                         "with a suppression)")
    # ---- module globals ---------------------------------------------
    guards_g: Dict[str, Set[str]] = {}
    for s in conc.summaries.values():
        for name, _, held, mutation in s.global_access:
            if mutation:
                locks = set(held) & set(conc.module_locks)
                if locks:
                    guards_g.setdefault(name, set()).update(locks)
    for s in conc.summaries.values():
        for name, site, held, mutation in s.global_access:
            guard = guards_g.get(name)
            if not guard or set(held) & guard:
                continue
            verb = "written" if mutation else "read"
            yield site, (f"module global `{name}` is {verb} in "
                         f"{s.name}() without {_fmt_locks(guard)}, which "
                         "guards its writes elsewhere — take the lock")


@register_rule(
    "lock-open-call", "P2",
    "A call to foreign code (another object's method, an imported "
    "function) while holding a lock: if the callee ever blocks or takes "
    "its own lock, the hold time — and the deadlock surface — is no "
    "longer yours to reason about. Prefer open calls: copy state under "
    "the lock, call outside it.")
def rule_lock_open_call(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    for s in conc.summaries.values():
        for held, site, desc in s.opaque_under:
            yield site, (f"{desc} called while holding {_fmt_locks(held)} "
                         "— an open-call discipline keeps foreign code "
                         "outside critical sections; copy under the lock, "
                         "call after release")


#: Constructions the concurrency seam (p2pnetwork_tpu/concurrency.py)
#: owns: building one of these directly bypasses the seam, so graftrace
#: can neither schedule nor observe it. ``threading.local`` is absent
#: deliberately (thread-local storage is not a synchronization
#: primitive), as is ``threading.current_thread`` (a query, not a
#: construction).
_RAW_PRIMITIVES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Thread", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.Timer",
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "time.sleep",
})

_SEAM_EQUIVALENT = {
    "threading.Lock": "concurrency.lock()",
    "threading.RLock": "concurrency.rlock()",
    "threading.Condition": "concurrency.condition()",
    "threading.Event": "concurrency.event()",
    "threading.Thread": "concurrency.thread(...)",
    "queue.Queue": "concurrency.fifo_queue()",
    "time.sleep": "concurrency.sleep()",
}


@register_rule(
    "raw-concurrency-primitive", "P2",
    "A threading/queue primitive (or time.sleep) is constructed directly "
    "instead of through the p2pnetwork_tpu.concurrency seam: graftrace "
    "cannot schedule or observe it, so the deterministic-concurrency "
    "gate silently loses coverage of whatever it guards.")
def rule_raw_concurrency_primitive(module: Module
                                   ) -> Iterable[Tuple[ast.AST, str]]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = resolve_dotted(module, node.func)
        if resolved not in _RAW_PRIMITIVES:
            continue
        hint = _SEAM_EQUIVALENT.get(
            resolved, "a p2pnetwork_tpu.concurrency factory")
        yield node, (f"direct {resolved}(...) bypasses the concurrency "
                     f"seam — use {hint} so graftrace can instrument it "
                     "(or suppress with the rationale that this one must "
                     "stay raw)")


@register_rule(
    "wait-untimed", "P2",
    "An unbounded cross-thread wait (.wait()/.result()/.join() with no "
    "timeout): if the other side is wedged, the caller hangs forever — "
    "bound it and surface the timeout as a structured error.")
def rule_wait_untimed(module: Module) -> Iterable[Tuple[ast.AST, str]]:
    conc = _concurrency(module)
    for s in conc.summaries.values():
        if s.is_async:
            continue  # the async variants are async-blocking-call's beat
        for held, site, desc, in_await in s.blocking:
            if held or in_await or not desc.startswith("untimed ."):
                continue
            yield site, (f"{desc.replace('untimed ', '')} with no timeout "
                         "— a wedged counterpart hangs this thread "
                         "forever; pass a bound and handle the timeout")
