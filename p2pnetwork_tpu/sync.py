"""Merkle-tree set reconciliation over the sockets backend.

*Make our stores equal without shipping the whole store* — the sync
problem every replicated system built on overlays like the reference
solves by hand (its dict messages give transport, nothing above it
[ref: README.md:20, p2pnetwork/nodeconnection.py:128-143]). The classic
answer (Merkle 1979; Dynamo/Cassandra anti-entropy, git's object
exchange): arrange item hashes in a hash trie, compare roots, and
descend only into subtrees whose hashes differ — identical stores cost
one round trip, a k-item difference costs O(k · log n) messages however
large the stores are.

:class:`SyncNode` keeps a dict store and a 16-way hash trie over it:

- items live at the hex-digit path of ``blake2b(key)``; every trie
  node's hash folds its children's items, so any single difference
  changes the root;
- :meth:`sync_with` sends our root. On mismatch the PEER walks the
  trie down (``_ms_tree`` / ``_ms_children``), pulling the subtrees it
  lacks (``_ms_pull``) and shipping the ones we lack (``_ms_items``) —
  one walker converges BOTH replicas to the union, and a ``_ms_done``
  (sent after the ships, FIFO-ordered behind them) tells the initiator
  its side is complete too;
- conflicting values for one key resolve deterministically: the
  lexicographically greater value wins on both sides (a documented
  arbitrary-but-convergent rule — bring your own versioning for real
  last-writer-wins semantics).

The sync counter (``sync_messages_sent``) makes the efficiency claim
testable: the suite pins that a 1-item diff over a 500-item store moves
a couple dozen messages, not 500 (tests/test_sync.py).

All state mutates on the node's event loop; :meth:`put` posts there and
:meth:`wait_synced` blocks the caller until the session with a peer has
quiesced on OUR side.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

FANOUT = 16  # one hex digit per trie level
#: Past this depth a prefix's items ship wholesale (hash collisions on a
#: 128-bit digest never get here; it bounds the walk on any key set).
MAX_DEPTH = 8


def _key_digest(key: str) -> str:
    return hashlib.blake2b(key.encode(), digest_size=16).hexdigest()


def _item_hash(key: str, value: str) -> str:
    return hashlib.blake2b(f"{key}\x00{value}".encode(),
                           digest_size=16).hexdigest()


class SyncNode(Node):
    """A :class:`Node` whose dict store reconciles via Merkle descent.

    Values are strings (serialize structured values yourself — the
    deterministic conflict rule compares the serialized form)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.store: Dict[str, str] = {}
        self._digests: Dict[str, str] = {}  # key -> hex digest (cached)
        self.sync_messages_sent = 0
        self._sync_events: Dict[str, Any] = {}  # peer id -> seam event
        self._walk_pending: Dict[str, int] = {}  # peer id -> open requests
        #: peer id -> root hash from an ``_ms_root`` that arrived while
        #: our walk with that peer was still mid-flight; consumed by
        #: :meth:`_quiesce` to start a follow-up walk.
        self._pending_root: Dict[str, str] = {}

    # ------------------------------------------------------------ app API

    def put(self, key: str, value: str) -> None:
        """Insert an item (posted onto the event loop). Overwrites only
        with a GREATER value — the convergence rule, applied locally too
        so replicas can't be driven apart by local writes mid-sync."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")
        loop.call_soon_threadsafe(self._put_local, key, value)

    def get(self, key: str) -> Optional[str]:
        return self.store.get(key)

    def sync_with(self, n: NodeConnection) -> None:
        """Start a reconciliation session with peer ``n`` (thread-safe).
        Both stores converge to the union; block on :meth:`wait_synced`."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")

        # Clear SYNCHRONOUSLY: posted to the loop, a caller's immediate
        # wait_synced could observe the previous session's still-set
        # event and return before this session even started.
        self._sync_events.setdefault(n.id, concurrency.event()).clear()

        def _do():
            self._send(n, {"_ms_root": self._subtree_hash("")})

        loop.call_soon_threadsafe(_do)

    def wait_synced(self, peer_id: str,
                    timeout: Optional[float] = None) -> bool:
        """Block until the session with ``peer_id`` has quiesced on our
        side (initiator: the peer's ``done`` arrived after its ships;
        responder: our walk's pulls all answered). A peer dying
        mid-session also releases the wait — quiesced is not converged
        then; check the peer's liveness if the distinction matters."""
        return self._sync_events.setdefault(
            peer_id, concurrency.event()).wait(timeout)

    def sync_complete(self, peer_id: str) -> None:
        """Our side of a sync session quiesced. Extension hook."""
        self.debug_print(f"sync_complete: {peer_id}")
        self._dispatch("sync_complete", None, {"peer_id": peer_id})

    # ------------------------------------------------------------- store

    def _put_local(self, key: str, value: str) -> None:
        old = self.store.get(key)
        if old is None or value > old:
            self.store[key] = value
            self._digests[key] = _key_digest(key)

    def _subtree_hash(self, prefix: str) -> str:
        h = hashlib.blake2b(digest_size=16)
        for key in sorted(k for k, d in self._digests.items()
                          if d.startswith(prefix)):
            h.update(_item_hash(key, self.store[key]).encode())
        return h.hexdigest()

    def _children_hashes(self, prefix: str) -> Dict[str, str]:
        # One pass over the store, bucketed by the next digest digit
        # (the naive per-child form scanned the whole store 32 times per
        # _ms_tree request). Key-sorted within each bucket — the same
        # order _subtree_hash uses, so the hashes agree.
        level = len(prefix)
        buckets: Dict[str, list] = {}
        for k, d in self._digests.items():
            if d.startswith(prefix):
                buckets.setdefault(d[: level + 1], []).append(k)
        out = {}
        for p, keys in buckets.items():
            h = hashlib.blake2b(digest_size=16)
            for key in sorted(keys):
                h.update(_item_hash(key, self.store[key]).encode())
            out[p] = h.hexdigest()
        return out

    def _items_under(self, prefix: str):
        return [(k, self.store[k]) for k, d in self._digests.items()
                if d.startswith(prefix)]

    # ---------------------------------------------------------- protocol

    def _send(self, n: NodeConnection, payload: dict) -> None:
        self.sync_messages_sent += 1
        self.send_to_node(n, payload)

    def _quiesce(self, n: NodeConnection, notify_peer: bool) -> None:
        # A fresh initiation from this peer landed while our walk was
        # mid-flight (see node_message's _ms_root branch): the active
        # walk may have passed subtrees BEFORE the peer put the items
        # that prompted its initiation, so releasing the peer's wait now
        # could leave the stores unequal. Run one follow-up walk first;
        # its quiesce releases both sides (or consumes yet another
        # queued root — each follow-up consumes exactly one, so this
        # terminates once initiations stop).
        pending = self._pending_root.pop(n.id, None)
        if pending is not None and pending != self._subtree_hash(""):
            self._bump(n, +1)
            self._send(n, {"_ms_tree": ""})
            return
        if notify_peer:
            self._send(n, {"_ms_done": True})
        self._sync_events.setdefault(n.id, concurrency.event()).set()
        self.sync_complete(n.id)

    def _bump(self, n: NodeConnection, delta: int) -> None:
        c = self._walk_pending.get(n.id, 0) + delta
        self._walk_pending[n.id] = c
        if c <= 0:
            self._walk_pending[n.id] = 0
            # Walk finished: our pulls are in; the peer already holds
            # every item we shipped (FIFO puts them before this done).
            self._quiesce(n, notify_peer=True)

    def _descend(self, n: NodeConnection, prefix: str,
                 remote_children: Dict[str, str]) -> None:
        """Compare the peer's child hashes under ``prefix`` to ours;
        pull what differs toward us, ship what they lack."""
        mine = self._children_hashes(prefix)
        for p in sorted(set(mine) | set(remote_children)):
            if mine.get(p) == remote_children.get(p):
                continue
            if p not in remote_children:
                # They have nothing under p: ship our items outright.
                self._send(n, {"_ms_items": self._items_under(p),
                               "_ms_ship": True})
            elif p not in mine:
                # We have nothing under p: ask for their items wholesale.
                self._bump(n, +1)
                self._send(n, {"_ms_pull": p})
            elif len(p) >= MAX_DEPTH:
                # Depth bound with both sides populated: same-key value
                # CONFLICTS land here (one key, one digest path, two
                # values), so the exchange must go BOTH ways — a pull
                # alone would resolve the conflict on this side only.
                self._send(n, {"_ms_items": self._items_under(p),
                               "_ms_ship": True})
                self._bump(n, +1)
                self._send(n, {"_ms_pull": p})
            else:
                # Both populated, hashes differ: walk down.
                self._bump(n, +1)
                self._send(n, {"_ms_tree": p})

    def node_message(self, node: NodeConnection, data) -> None:
        if not isinstance(data, dict):
            return super().node_message(node, data)
        if "_ms_root" in data:
            # Session start (we are the responder / walker). If OUR walk
            # with this peer is already mid-flight (simultaneous mutual
            # initiation or re-initiation), don't reset its accounting —
            # queue the root instead: the active walk may already have
            # passed subtrees the peer mutated after it visited them, so
            # _quiesce runs a follow-up walk before releasing the
            # peer's wait (tests/test_sync.py::test_reinitiation_mid_walk).
            if self._walk_pending.get(node.id, 0) > 0:
                self._pending_root[node.id] = data["_ms_root"]
                return
            self._sync_events.setdefault(node.id,
                                         concurrency.event()).clear()
            self._walk_pending[node.id] = 0
            if data["_ms_root"] == self._subtree_hash(""):
                self._quiesce(node, notify_peer=True)
            else:
                self._bump(node, +1)
                self._send(node, {"_ms_tree": ""})
            return
        if "_ms_tree" in data:
            p = data["_ms_tree"]
            self._send(node, {"_ms_children": p,
                              "hashes": self._children_hashes(p)})
            return
        if "_ms_children" in data:
            self._descend(node, data["_ms_children"], data["hashes"])
            self._bump(node, -1)  # this walk request resolved
            return
        if "_ms_pull" in data:
            self._send(node, {"_ms_items":
                              self._items_under(data["_ms_pull"])})
            return
        if "_ms_items" in data:
            for k, v in data["_ms_items"]:
                self._put_local(k, v)
            if not data.get("_ms_ship"):
                self._bump(node, -1)  # a pull of ours was answered
            return
        if "_ms_done" in data:
            # The walker finished: its ships precede this on the FIFO
            # stream, so our store already holds everything.
            self._quiesce(node, notify_peer=False)
            return
        super().node_message(node, data)

    def node_disconnected(self, node: NodeConnection) -> None:
        # A peer dying mid-session would otherwise leave waiters blocked
        # for their full timeout: release the session. wait_synced then
        # returns — QUIESCED, not necessarily converged; callers who care
        # can check the peer's liveness before trusting the cut.
        if node.id in self._walk_pending:
            self._walk_pending[node.id] = 0
        self._pending_root.pop(node.id, None)
        ev = self._sync_events.get(node.id)
        if ev is not None and not ev.is_set():
            ev.set()
        super().node_disconnected(node)
