// graphcore: native kernels for host-side graph construction.
//
// The reference is pure Python end to end (SURVEY.md section 2.1: zero
// native components), so nothing here is a port — this is the runtime-side
// native layer of the TPU framework: the device hot path is XLA/Pallas,
// and the host hot path (building million-node graphs: sorting edge lists,
// deduplicating undirected pairs) is C++ behind a ctypes boundary with a
// numpy fallback (p2pnetwork_tpu/native/__init__.py).
//
// Build: g++ -O3 -shared -fPIC graphcore.cpp -o libgraphcore.so
// (done on demand by the Python loader; no build system required).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// One LSD counting pass: stable-sort (key, val) by bits [shift, shift+16).
template <typename K>
void counting_pass(const K* sk, const int32_t* sv, K* dk, int32_t* dv,
                   int64_t n, int shift, int64_t* cnt) {
    constexpr int64_t R = 1 << 16;
    std::fill(cnt, cnt + R, 0);
    for (int64_t i = 0; i < n; ++i) cnt[(sk[i] >> shift) & 0xFFFF]++;
    int64_t sum = 0;
    for (int64_t b = 0; b < R; ++b) {
        int64_t c = cnt[b];
        cnt[b] = sum;
        sum += c;
    }
    for (int64_t i = 0; i < n; ++i) {
        int64_t pos = cnt[(sk[i] >> shift) & 0xFFFF]++;
        dk[pos] = sk[i];
        dv[pos] = sv[i];
    }
}

int passes_for(uint64_t max_key) {
    // Shift the key down instead of growing the shift count: a shift of
    // >= 64 bits (keys >= 2^48 under the old form) is undefined behavior
    // and an infinite loop on x86, where shift counts wrap mod 64.
    int p = 1;
    while (max_key >>= 16) ++p;
    return p;
}

}  // namespace

extern "C" {

// Stable sort of (key, val) int32 pairs by non-negative key.
// out arrays must not alias the inputs.
void gc_sort_pairs_i32(const int32_t* keys, const int32_t* vals, int64_t n,
                       int32_t* out_keys, int32_t* out_vals) {
    if (n <= 0) return;
    int32_t mx = 0;
    for (int64_t i = 0; i < n; ++i) mx = std::max(mx, keys[i]);
    int np = passes_for((uint64_t)mx);
    std::vector<int64_t> cnt(1 << 16);
    std::vector<int32_t> tk(n), tv(n);
    // Ping-pong between the temp and out buffers so the final pass lands in
    // out; with an odd pass count start temp-first, else out-first.
    int32_t* bufk[2] = {tk.data(), out_keys};
    int32_t* bufv[2] = {tv.data(), out_vals};
    int dst = (np % 2 == 1) ? 1 : 0;
    const int32_t* sk = keys;
    const int32_t* sv = vals;
    for (int p = 0; p < np; ++p) {
        counting_pass(sk, sv, bufk[dst], bufv[dst], n, 16 * p, cnt.data());
        sk = bufk[dst];
        sv = bufv[dst];
        dst ^= 1;
    }
    if (sk != out_keys) {
        std::memcpy(out_keys, sk, n * sizeof(int32_t));
        std::memcpy(out_vals, sv, n * sizeof(int32_t));
    }
}

// Sort non-negative int64 keys ascending, drop duplicates in place;
// returns the unique count.
int64_t gc_sort_unique_i64(int64_t* keys, int64_t n) {
    if (n <= 0) return 0;
    uint64_t mx = 0;
    for (int64_t i = 0; i < n; ++i) mx = std::max(mx, (uint64_t)keys[i]);
    int np = passes_for(mx);
    constexpr int64_t R = 1 << 16;
    std::vector<int64_t> cnt(R);
    std::vector<int64_t> tmp(n);
    int64_t* src = keys;
    int64_t* dst = tmp.data();
    for (int p = 0; p < np; ++p) {
        int shift = 16 * p;
        std::fill(cnt.begin(), cnt.end(), 0);
        for (int64_t i = 0; i < n; ++i) cnt[(src[i] >> shift) & 0xFFFF]++;
        int64_t sum = 0;
        for (int64_t b = 0; b < R; ++b) {
            int64_t c = cnt[b];
            cnt[b] = sum;
            sum += c;
        }
        for (int64_t i = 0; i < n; ++i) dst[cnt[(src[i] >> shift) & 0xFFFF]++] = src[i];
        std::swap(src, dst);
    }
    if (src != keys) std::memcpy(keys, src, n * sizeof(int64_t));
    return std::unique(keys, keys + n) - keys;
}

}  // extern "C"
