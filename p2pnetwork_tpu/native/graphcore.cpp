// graphcore: native kernels for host-side graph construction.
//
// The reference is pure Python end to end (SURVEY.md section 2.1: zero
// native components), so nothing here is a port — this is the runtime-side
// native layer of the TPU framework: the device hot path is XLA/Pallas,
// and the host hot path (building million-node graphs: sorting edge lists,
// deduplicating undirected pairs) is C++ behind a ctypes boundary with a
// numpy fallback (p2pnetwork_tpu/native/__init__.py).
//
// Build: g++ -O3 -shared -fPIC graphcore.cpp -o libgraphcore.so
// (done on demand by the Python loader; no build system required).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// One LSD counting pass: stable-sort (key, val) by bits [shift, shift+16).
template <typename K>
void counting_pass(const K* sk, const int32_t* sv, K* dk, int32_t* dv,
                   int64_t n, int shift, int64_t* cnt) {
    constexpr int64_t R = 1 << 16;
    std::fill(cnt, cnt + R, 0);
    for (int64_t i = 0; i < n; ++i) cnt[(sk[i] >> shift) & 0xFFFF]++;
    int64_t sum = 0;
    for (int64_t b = 0; b < R; ++b) {
        int64_t c = cnt[b];
        cnt[b] = sum;
        sum += c;
    }
    for (int64_t i = 0; i < n; ++i) {
        int64_t pos = cnt[(sk[i] >> shift) & 0xFFFF]++;
        dk[pos] = sk[i];
        dv[pos] = sv[i];
    }
}

int passes_for(uint64_t max_key) {
    // Shift the key down instead of growing the shift count: a shift of
    // >= 64 bits (keys >= 2^48 under the old form) is undefined behavior
    // and an infinite loop on x86, where shift counts wrap mod 64.
    int p = 1;
    while (max_key >>= 16) ++p;
    return p;
}

}  // namespace

extern "C" {

// Stable sort of (key, val) int32 pairs by non-negative key.
// out arrays must not alias the inputs.
void gc_sort_pairs_i32(const int32_t* keys, const int32_t* vals, int64_t n,
                       int32_t* out_keys, int32_t* out_vals) {
    if (n <= 0) return;
    int32_t mx = 0;
    for (int64_t i = 0; i < n; ++i) mx = std::max(mx, keys[i]);
    int np = passes_for((uint64_t)mx);
    std::vector<int64_t> cnt(1 << 16);
    std::vector<int32_t> tk(n), tv(n);
    // Ping-pong between the temp and out buffers so the final pass lands in
    // out; with an odd pass count start temp-first, else out-first.
    int32_t* bufk[2] = {tk.data(), out_keys};
    int32_t* bufv[2] = {tv.data(), out_vals};
    int dst = (np % 2 == 1) ? 1 : 0;
    const int32_t* sk = keys;
    const int32_t* sv = vals;
    for (int p = 0; p < np; ++p) {
        counting_pass(sk, sv, bufk[dst], bufv[dst], n, 16 * p, cnt.data());
        sk = bufk[dst];
        sv = bufv[dst];
        dst ^= 1;
    }
    if (sk != out_keys) {
        std::memcpy(out_keys, sk, n * sizeof(int32_t));
        std::memcpy(out_vals, sv, n * sizeof(int32_t));
    }
}

// ---------------------------------------------------------------- deltas
//
// Incremental (delta) graph builds: the base COO edge arrays are already
// receiver-sorted, so applying an add/remove batch never needs the full
// radix sort again — only the delta is sorted (gc_sort_pairs_i32 above),
// then these linear passes merge/anti-merge it into the base order. All
// of them are single sweeps with no allocation; the Python layer
// (sim/graph.py apply_delta) owns the padding and bookkeeping.

// Anti-merge: mark which base edges survive a removal batch. The base
// arrays are the full padded COO (receiver-sorted among live slots);
// alive[i] != 0 marks live slots. Removals (rr, rs) must be sorted by
// (receiver, sender). keep[i] is set to 1 exactly for live, un-removed
// edges; rem_hits[j] counts how many live copies removal j matched (the
// caller raises on zeros — removing an absent edge is a bug, not a
// no-op). Returns the kept count.
int64_t gc_delta_antimerge_i32(const int32_t* br, const int32_t* bs,
                               const uint8_t* alive, int64_t nb,
                               const int32_t* rr, const int32_t* rs,
                               int64_t nr, uint8_t* keep,
                               int32_t* rem_hits) {
    // Removal-driven: keep starts as the liveness mask (one memcpy), then
    // each removal binary-searches its receiver's contiguous run and
    // clears the matching copies — O(removals * (log E + run width)), no
    // O(E) sweep at all. The padded receiver array is globally sorted
    // (padding holds the max id), so the search covers dead slots too;
    // the alive[] check skips them.
    std::memcpy(keep, alive, nb);
    int64_t cleared = 0;
    int64_t lo = 0, hi = 0;
    int32_t win_r = -1;
    for (int64_t j = 0; j < nr; ++j) {
        if (rr[j] != win_r) {  // removals sorted by (receiver, sender)
            lo = std::lower_bound(br, br + nb, rr[j]) - br;
            hi = std::upper_bound(br + lo, br + nb, rr[j]) - br;
            win_r = rr[j];
        }
        int32_t hits = 0;
        for (int64_t i = lo; i < hi; ++i) {
            if (alive[i] && bs[i] == rs[j]) {
                ++hits;  // every live copy counts, duplicates included
                if (keep[i]) {
                    keep[i] = 0;
                    ++cleared;
                }
            }
        }
        rem_hits[j] = hits;
    }
    return cleared;
}

// Stable merge of the kept base edges with a receiver-sorted delta batch
// (base first on equal receivers — exactly the order a stable from-scratch
// sort of [kept base, delta] would produce). Writes the merged
// receiver/sender arrays plus each side's landing position: posa[i] is the
// merged index of base slot i (-1 for dropped slots), posb[j] the merged
// index of delta entry j. Returns the merged count.
int64_t gc_delta_merge_i32(const int32_t* br, const int32_t* bs,
                           const uint8_t* keep, int64_t nb,
                           const int32_t* dr, const int32_t* ds, int64_t nd,
                           int32_t* out_r, int32_t* out_s,
                           int32_t* posa, int32_t* posb) {
    int64_t out = 0, j = 0;
    for (int64_t i = 0; i < nb; ++i) {
        if (!keep[i]) {
            posa[i] = -1;
            continue;
        }
        while (j < nd && dr[j] < br[i]) {
            out_r[out] = dr[j];
            out_s[out] = ds[j];
            posb[j++] = (int32_t)out++;
        }
        out_r[out] = br[i];
        out_s[out] = bs[i];
        posa[i] = (int32_t)out++;
    }
    while (j < nd) {
        out_r[out] = dr[j];
        out_s[out] = ds[j];
        posb[j++] = (int32_t)out++;
    }
    return out;
}

// Remap an edge-id list through a position map, dropping entries that map
// to -1 (removed edges). Order-preserving; returns the surviving count.
int64_t gc_map_filter_i32(const int32_t* eids, int64_t n,
                          const int32_t* pos, int32_t* out) {
    int64_t m = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t p = pos[eids[i]];
        if (p >= 0) out[m++] = p;
    }
    return m;
}

// Merge two edge-id lists, each already sorted by (senders[eid], eid)
// ascending, preserving that order — the incremental source-CSR update
// (sim/graph.py apply_delta): the surviving old CSR order merged with the
// delta's sender-sorted ids replaces a full radix re-sort of E edges.
void gc_merge_eids_by_sender_i32(const int32_t* senders, const int32_t* ea,
                                 int64_t na, const int32_t* eb, int64_t nb,
                                 int32_t* out) {
    int64_t i = 0, j = 0, o = 0;
    while (i < na && j < nb) {
        int32_t sa = senders[ea[i]], sb = senders[eb[j]];
        if (sa < sb || (sa == sb && ea[i] < eb[j]))
            out[o++] = ea[i++];
        else
            out[o++] = eb[j++];
    }
    while (i < na) out[o++] = ea[i++];
    while (j < nb) out[o++] = eb[j++];
}

// Sort non-negative int64 keys ascending, drop duplicates in place;
// returns the unique count.
int64_t gc_sort_unique_i64(int64_t* keys, int64_t n) {
    if (n <= 0) return 0;
    uint64_t mx = 0;
    for (int64_t i = 0; i < n; ++i) mx = std::max(mx, (uint64_t)keys[i]);
    int np = passes_for(mx);
    constexpr int64_t R = 1 << 16;
    std::vector<int64_t> cnt(R);
    std::vector<int64_t> tmp(n);
    int64_t* src = keys;
    int64_t* dst = tmp.data();
    for (int p = 0; p < np; ++p) {
        int shift = 16 * p;
        std::fill(cnt.begin(), cnt.end(), 0);
        for (int64_t i = 0; i < n; ++i) cnt[(src[i] >> shift) & 0xFFFF]++;
        int64_t sum = 0;
        for (int64_t b = 0; b < R; ++b) {
            int64_t c = cnt[b];
            cnt[b] = sum;
            sum += c;
        }
        for (int64_t i = 0; i < n; ++i) dst[cnt[(src[i] >> shift) & 0xFFFF]++] = src[i];
        std::swap(src, dst);
    }
    if (src != keys) std::memcpy(keys, src, n * sizeof(int64_t));
    return std::unique(keys, keys + n) - keys;
}

}  // extern "C"
