"""Native (C++) host kernels with transparent numpy fallback.

The device hot path of this framework is XLA/Pallas; the *host* hot path is
graph construction — sorting multi-million-edge lists and deduplicating
undirected pairs, which dominates wall clock at BASELINE scale when done
with numpy's comparison sorts. ``graphcore.cpp`` implements them as LSD
radix passes; this module compiles it on first use (``g++ -O3 -shared``,
cached next to the source) and binds it with ctypes — no build system, no
binding generator, and every entry point silently falls back to numpy when
a compiler is unavailable (``force_fallback()`` pins that for tests).

The reference has no native code at all (SURVEY.md section 2.1); this layer
exists because the new framework builds graphs five orders of magnitude
larger than a reference process would hold sockets.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_SRC = Path(__file__).with_name("graphcore.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_forced_fallback = False


def force_fallback(enabled: bool = True) -> None:
    """Disable (or re-enable) the native library — numpy paths only."""
    global _forced_fallback
    _forced_fallback = enabled


def _so_candidates():
    """Where the compiled library may live: next to the source (dev
    checkout), else a per-user cache dir (read-only installs)."""
    yield _SRC.with_name("libgraphcore.so")
    cache = Path(os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache"))
    yield cache / "p2pnetwork_tpu" / "libgraphcore.so"


def _compile() -> Optional[Path]:
    """Compile (or find cached) libgraphcore.so; None means use numpy.

    Every filesystem/toolchain failure is swallowed — the contract of this
    module is a silent numpy fallback, never an import-time crash.
    """
    try:
        src_mtime = _SRC.stat().st_mtime
    except OSError:
        return None  # source not shipped (e.g. a .py-only wheel)
    for so in _so_candidates():
        try:
            if so.exists() and so.stat().st_mtime >= src_mtime:
                return so
        except OSError:
            continue
    for so in _so_candidates():
        try:
            so.parent.mkdir(parents=True, exist_ok=True)
            # Build into a temp file then rename: concurrent importers must
            # never dlopen a half-written .so.
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(so.parent))
            os.close(fd)
        except OSError:
            continue
        cmd = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
            return so
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None  # compiler failure will not differ by directory
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _forced_fallback:
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _compile()  # graftlint: ignore[blocking-under-lock] -- the lock EXISTS to serialize the build-once; concurrent callers must block until the .so exists
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(str(so))  # graftlint: ignore[lock-open-call] -- same build-once critical section
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")  # graftlint: ignore[lock-open-call] -- pure ctypes type ctor
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")  # graftlint: ignore[lock-open-call] -- pure ctypes type ctor
            lib.gc_sort_pairs_i32.argtypes = [i32p, i32p, ctypes.c_int64, i32p, i32p]
            lib.gc_sort_pairs_i32.restype = None
            lib.gc_sort_unique_i64.argtypes = [i64p, ctypes.c_int64]
            lib.gc_sort_unique_i64.restype = ctypes.c_int64
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    """True when the native library is loaded (compiles on first call)."""
    return _load() is not None


def sort_pairs(keys: np.ndarray, vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Stable sort of (keys, vals) by non-negative int32 ``keys``.

    Equivalent to ``order = np.argsort(keys, kind="stable");
    (keys[order], vals[order])`` — radix passes instead of comparison sort.
    """
    keys = np.ascontiguousarray(keys, dtype=np.int32)
    vals = np.ascontiguousarray(vals, dtype=np.int32)
    if keys.shape != vals.shape or keys.ndim != 1:
        raise ValueError("sort_pairs expects two equal-length 1-D arrays")
    lib = _load()
    if lib is None or keys.size == 0:
        order = np.argsort(keys, kind="stable")
        return keys[order], vals[order]
    out_k = np.empty_like(keys)
    out_v = np.empty_like(vals)
    lib.gc_sort_pairs_i32(keys, vals, keys.size, out_k, out_v)
    return out_k, out_v


def sort_unique(keys: np.ndarray) -> np.ndarray:
    """Sorted unique non-negative int64 ``keys`` (``np.unique`` equivalent)."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if keys.ndim != 1:
        raise ValueError("sort_unique expects a 1-D array")
    lib = _load()
    if lib is None or keys.size == 0:
        return np.unique(keys)
    buf = keys.copy()
    m = lib.gc_sort_unique_i64(buf, buf.size)
    return buf[:m]
