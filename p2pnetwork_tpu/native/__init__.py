"""Native (C++) host kernels with transparent numpy fallback.

The device hot path of this framework is XLA/Pallas; the *host* hot path is
graph construction — sorting multi-million-edge lists and deduplicating
undirected pairs, which dominates wall clock at BASELINE scale when done
with numpy's comparison sorts. ``graphcore.cpp`` implements them as LSD
radix passes; this module compiles it on first use (``g++ -O3 -shared``,
cached next to the source) and binds it with ctypes — no build system, no
binding generator, and every entry point silently falls back to numpy when
a compiler is unavailable (``force_fallback()`` pins that for tests).

The reference has no native code at all (SURVEY.md section 2.1); this layer
exists because the new framework builds graphs five orders of magnitude
larger than a reference process would hold sockets.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from p2pnetwork_tpu import concurrency

_SRC = Path(__file__).with_name("graphcore.cpp")

_lock = concurrency.lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_forced_fallback = False


def force_fallback(enabled: bool = True) -> None:
    """Disable (or re-enable) the native library — numpy paths only."""
    global _forced_fallback
    _forced_fallback = enabled


def _so_candidates():
    """Where the compiled library may live: next to the source (dev
    checkout), else a per-user cache dir (read-only installs)."""
    yield _SRC.with_name("libgraphcore.so")
    cache = Path(os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache"))
    yield cache / "p2pnetwork_tpu" / "libgraphcore.so"


def _compile() -> Optional[Path]:
    """Compile (or find cached) libgraphcore.so; None means use numpy.

    Every filesystem/toolchain failure is swallowed — the contract of this
    module is a silent numpy fallback, never an import-time crash.
    """
    try:
        src_mtime = _SRC.stat().st_mtime
    except OSError:
        return None  # source not shipped (e.g. a .py-only wheel)
    for so in _so_candidates():
        try:
            if so.exists() and so.stat().st_mtime >= src_mtime:
                return so
        except OSError:
            continue
    for so in _so_candidates():
        try:
            so.parent.mkdir(parents=True, exist_ok=True)
            # Build into a temp file then rename: concurrent importers must
            # never dlopen a half-written .so.
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(so.parent))
            os.close(fd)
        except OSError:
            continue
        cmd = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
            return so
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None  # compiler failure will not differ by directory
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _forced_fallback:
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _compile()  # graftlint: ignore[blocking-under-lock] -- the lock EXISTS to serialize the build-once; concurrent callers must block until the .so exists
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(str(so))  # graftlint: ignore[lock-open-call] -- same build-once critical section
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")  # graftlint: ignore[lock-open-call] -- pure ctypes type ctor
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")  # graftlint: ignore[lock-open-call] -- pure ctypes type ctor
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")  # graftlint: ignore[lock-open-call] -- pure ctypes type ctor
            lib.gc_sort_pairs_i32.argtypes = [i32p, i32p, ctypes.c_int64, i32p, i32p]
            lib.gc_sort_pairs_i32.restype = None
            lib.gc_sort_unique_i64.argtypes = [i64p, ctypes.c_int64]
            lib.gc_sort_unique_i64.restype = ctypes.c_int64
            lib.gc_delta_antimerge_i32.argtypes = [
                i32p, i32p, u8p, ctypes.c_int64, i32p, i32p, ctypes.c_int64,
                u8p, i32p]
            lib.gc_delta_antimerge_i32.restype = ctypes.c_int64
            lib.gc_delta_merge_i32.argtypes = [
                i32p, i32p, u8p, ctypes.c_int64, i32p, i32p, ctypes.c_int64,
                i32p, i32p, i32p, i32p]
            lib.gc_delta_merge_i32.restype = ctypes.c_int64
            lib.gc_map_filter_i32.argtypes = [i32p, ctypes.c_int64, i32p, i32p]
            lib.gc_map_filter_i32.restype = ctypes.c_int64
            lib.gc_merge_eids_by_sender_i32.argtypes = [
                i32p, i32p, ctypes.c_int64, i32p, ctypes.c_int64, i32p]
            lib.gc_merge_eids_by_sender_i32.restype = None
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    """True when the native library is loaded (compiles on first call)."""
    return _load() is not None


def sort_pairs(keys: np.ndarray, vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Stable sort of (keys, vals) by non-negative int32 ``keys``.

    Equivalent to ``order = np.argsort(keys, kind="stable");
    (keys[order], vals[order])`` — radix passes instead of comparison sort.
    """
    keys = np.ascontiguousarray(keys, dtype=np.int32)
    vals = np.ascontiguousarray(vals, dtype=np.int32)
    if keys.shape != vals.shape or keys.ndim != 1:
        raise ValueError("sort_pairs expects two equal-length 1-D arrays")
    lib = _load()
    if lib is None or keys.size == 0:
        order = np.argsort(keys, kind="stable")
        return keys[order], vals[order]
    out_k = np.empty_like(keys)
    out_v = np.empty_like(vals)
    lib.gc_sort_pairs_i32(keys, vals, keys.size, out_k, out_v)
    return out_k, out_v


def sort_unique(keys: np.ndarray) -> np.ndarray:
    """Sorted unique non-negative int64 ``keys`` (``np.unique`` equivalent)."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if keys.ndim != 1:
        raise ValueError("sort_unique expects a 1-D array")
    lib = _load()
    if lib is None or keys.size == 0:
        return np.unique(keys)
    buf = keys.copy()
    m = lib.gc_sort_unique_i64(buf, buf.size)
    return buf[:m]


# ------------------------------------------------------------ delta builds
#
# Host kernels behind sim/graph.py's apply_delta: the base COO arrays are
# already receiver-sorted, so an add/remove batch only needs the DELTA
# radix-sorted (sort_pairs above) plus these linear merge/anti-merge
# passes — never the full E-element sort a from-scratch build pays. Each
# has a vectorized numpy fallback honoring force_fallback().

def _pair_keys(r: np.ndarray, s: np.ndarray) -> np.ndarray:
    """int64 (receiver, sender) keys ordering like the lexicographic pair —
    both ids are non-negative int32, so 32-bit shifting cannot collide."""
    return (r.astype(np.int64) << 32) | s.astype(np.int64)


def delta_antimerge(base_r: np.ndarray, base_s: np.ndarray,
                    alive: np.ndarray, rem_r: np.ndarray,
                    rem_s: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Survivor mask of the base COO under a removal batch.

    ``base_r``/``base_s`` are the full padded edge arrays (receiver-sorted
    among live slots), ``alive`` the liveness mask; ``rem_r``/``rem_s``
    must be sorted by (receiver, sender). Returns ``(keep, matched)``:
    ``keep`` marks live edges NOT removed; ``matched[j]`` says removal
    ``j`` hit at least one live copy (every copy of a matched pair is
    removed). Callers decide whether unmatched removals are an error.
    """
    base_r = np.ascontiguousarray(base_r, dtype=np.int32)
    base_s = np.ascontiguousarray(base_s, dtype=np.int32)
    alive_u8 = np.ascontiguousarray(alive, dtype=np.uint8)
    rem_r = np.ascontiguousarray(rem_r, dtype=np.int32)
    rem_s = np.ascontiguousarray(rem_s, dtype=np.int32)
    lib = _load()
    if lib is not None and base_r.size and rem_r.size:
        keep = np.empty(base_r.size, dtype=np.uint8)
        hits = np.empty(rem_r.size, dtype=np.int32)
        lib.gc_delta_antimerge_i32(base_r, base_s, alive_u8, base_r.size,
                                   rem_r, rem_s, rem_r.size, keep, hits)
        return keep.view(bool), hits > 0
    keep = alive_u8.astype(bool)
    if rem_r.size == 0 or base_r.size == 0:
        return keep, np.zeros(rem_r.size, dtype=bool)
    bk = _pair_keys(base_r, base_s)
    rk = _pair_keys(rem_r, rem_s)
    uk = np.unique(rk)
    pos = np.searchsorted(uk, bk)
    hit = keep & (uk[np.minimum(pos, uk.size - 1)] == bk)
    matched_unique = np.zeros(uk.size, dtype=bool)
    matched_unique[pos[hit]] = True
    return keep & ~hit, matched_unique[np.searchsorted(uk, rk)]


def delta_merge(base_r: np.ndarray, base_s: np.ndarray, keep: np.ndarray,
                d_r: np.ndarray, d_s: np.ndarray,
                out_r: Optional[np.ndarray] = None,
                out_s: Optional[np.ndarray] = None):
    """Stable merge of the kept base edges with a receiver-sorted delta
    (base first on ties — the order a stable from-scratch sort of
    ``[kept base, delta]`` yields). Returns ``(out_r, out_s, posa, posb)``
    where ``posa[i]`` is base slot i's merged index (-1 when dropped) and
    ``posb[j]`` delta entry j's. ``out_r``/``out_s`` may be preallocated
    int32 buffers (at least merged-count long, e.g. the already-padded
    target arrays) — the merge then writes in place, skipping a copy."""
    base_r = np.ascontiguousarray(base_r, dtype=np.int32)
    base_s = np.ascontiguousarray(base_s, dtype=np.int32)
    keep_u8 = np.ascontiguousarray(keep, dtype=np.uint8)
    d_r = np.ascontiguousarray(d_r, dtype=np.int32)
    d_s = np.ascontiguousarray(d_s, dtype=np.int32)
    cap = base_r.size + d_r.size
    if out_r is None:
        out_r = np.empty(cap, dtype=np.int32)
        out_s = np.empty(cap, dtype=np.int32)
    lib = _load()
    if lib is not None and base_r.size:
        posa = np.empty(base_r.size, dtype=np.int32)
        posb = np.empty(d_r.size, dtype=np.int32)
        n = lib.gc_delta_merge_i32(base_r, base_s, keep_u8, base_r.size,
                                   d_r, d_s, d_r.size, out_r, out_s,
                                   posa, posb)
        return out_r[:n], out_s[:n], posa, posb
    kept_idx = np.flatnonzero(keep_u8)
    kr, ks = base_r[kept_idx], base_s[kept_idx]
    nk, nd = kr.size, d_r.size
    # Stable-merge positions via searchsorted: a kept base edge lands after
    # every strictly-smaller delta receiver; a delta edge lands after every
    # kept receiver <= its own (base wins ties).
    posk = np.arange(nk, dtype=np.int32) + np.searchsorted(
        d_r, kr, side="left").astype(np.int32)
    posd = np.arange(nd, dtype=np.int32) + np.searchsorted(
        kr, d_r, side="right").astype(np.int32)
    out_r[posk], out_s[posk] = kr, ks
    out_r[posd], out_s[posd] = d_r, d_s
    posa = np.full(base_r.size, -1, dtype=np.int32)
    posa[kept_idx] = posk
    return out_r[:nk + nd], out_s[:nk + nd], posa, posd


def map_filter(eids: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """``pos[eids]`` with the ``-1`` (dropped) entries filtered out,
    order-preserving — the surviving half of the incremental CSR update."""
    eids = np.ascontiguousarray(eids, dtype=np.int32)
    pos = np.ascontiguousarray(pos, dtype=np.int32)
    lib = _load()
    if lib is not None and eids.size:
        out = np.empty(eids.size, dtype=np.int32)
        m = lib.gc_map_filter_i32(eids, eids.size, pos, out)
        return out[:m]
    mapped = pos[eids]
    return mapped[mapped >= 0]


def merge_eids_by_sender(senders: np.ndarray, ea: np.ndarray,
                         eb: np.ndarray,
                         out: Optional[np.ndarray] = None) -> np.ndarray:
    """Merge two edge-id lists, each sorted by ``(senders[eid], eid)``,
    preserving that order — the incremental source-CSR merge. ``out`` may
    be a preallocated int32 buffer (exactly ``ea.size + eb.size`` long,
    e.g. a view of the padded target array) to write in place."""
    senders = np.ascontiguousarray(senders, dtype=np.int32)
    ea = np.ascontiguousarray(ea, dtype=np.int32)
    eb = np.ascontiguousarray(eb, dtype=np.int32)
    if out is None:
        out = np.empty(ea.size + eb.size, dtype=np.int32)
    lib = _load()
    if lib is not None and (ea.size or eb.size):
        lib.gc_merge_eids_by_sender_i32(senders, ea, ea.size, eb, eb.size,
                                        out)
        return out
    ka = (senders[ea].astype(np.int64) << 32) | ea
    kb = (senders[eb].astype(np.int64) << 32) | eb
    out[np.arange(ea.size) + np.searchsorted(kb, ka)] = ea
    out[np.arange(eb.size) + np.searchsorted(ka, kb, side="right")] = eb
    return out
