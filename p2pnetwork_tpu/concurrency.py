"""The concurrency seam: one factory for every thread/lock/queue primitive.

The sockets/chaos/supervise plane spans 30+ ``threading`` primitives
across 18 modules. graftlint (analysis/concurrency.py) reasons about
them statically; graftrace (analysis/race/) needs to OBSERVE them — to
serialize instrumented threads at acquire/release/wait/notify/put/get
boundaries under a seeded deterministic scheduler and derive
happens-before edges from what actually happened. That only works if
every primitive the plane uses is constructed through one seam a
test-time provider can substitute, instead of monkeypatching
``threading`` (which would also hijack the scheduler's own internals,
pytest, and every third-party library in the process).

So: production code in this package never calls ``threading.Lock()``,
``threading.Event()``, ``threading.Thread(...)``, ``queue.Queue()`` or
``time.sleep()`` directly — it calls :func:`lock`, :func:`event`,
:func:`thread`, :func:`fifo_queue`, :func:`sleep` here. With no provider
installed (the default, always in production) these return the stdlib
objects with zero added indirection per *use* — the substitution cost is
one guarded read at *construction* time only. graftlint's
``raw-concurrency-primitive`` rule keeps the seam from eroding: any
direct construction outside this module is a finding.

A provider is any object with the same-named factory methods
(``lock/rlock/condition/event/thread/fifo_queue/sleep``); graftrace's
:class:`~p2pnetwork_tpu.analysis.race.sched.TraceProvider` is the one
real implementation. Install is process-global and intended for
controlled test runs only — the graftrace driver installs around one
explored schedule and restores after.

Stdlib-only: the sockets backend must import this without jax installed.
"""

from __future__ import annotations

import queue as _queue_mod
import threading as _threading
import time as _time
from contextlib import contextmanager
from typing import Any, Callable, Optional

__all__ = [
    "lock", "rlock", "condition", "event", "thread", "fifo_queue",
    "sleep", "install", "installed", "substituted",
]

#: The active provider, or None for raw stdlib primitives. Swapped only
#: by graftrace around a controlled run; guarded so the swap and every
#: construction-time read agree (the discipline graftlint's lock-guard
#: rule checks).
_provider: Optional[Any] = None
# The seam's own bootstrap lock must be raw: it exists before any
# provider can, and instrumenting it would recurse.
_provider_lock = _threading.Lock()  # graftlint: ignore[raw-concurrency-primitive] -- the seam's bootstrap lock predates any provider


def _current() -> Optional[Any]:
    with _provider_lock:
        return _provider


def install(provider: Optional[Any]) -> Optional[Any]:
    """Swap the process-wide provider (``None`` restores raw stdlib
    primitives); returns the previous provider so callers can restore
    it. Prefer :func:`substituted` for scoped use."""
    global _provider
    with _provider_lock:
        prev, _provider = _provider, provider
    return prev


def installed() -> Optional[Any]:
    """The active provider, or ``None`` (raw stdlib)."""
    return _current()


@contextmanager
def substituted(provider: Optional[Any]):
    """Install ``provider`` for the duration of the block, restoring the
    previous provider (usually ``None``) on exit, even on error."""
    prev = install(provider)
    try:
        yield provider
    finally:
        install(prev)


# ------------------------------------------------------------- factories
#
# Each factory reads the provider under the seam lock, then constructs
# OUTSIDE it (open-call discipline: a provider factory is foreign code).
# The raw constructions below are the one sanctioned home of these
# calls; everywhere else they are graftlint findings.

def lock():
    """A mutex (``threading.Lock`` semantics: non-reentrant)."""
    p = _current()
    if p is None:
        return _threading.Lock()  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
    return p.lock()


def rlock():
    """A reentrant mutex (``threading.RLock`` semantics)."""
    p = _current()
    if p is None:
        return _threading.RLock()  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
    return p.rlock()


def condition(lock: Optional[Any] = None):
    """A condition variable (``threading.Condition`` semantics)."""
    p = _current()
    if p is None:
        return _threading.Condition(lock)  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
    return p.condition(lock)


def event():
    """A one-way flag (``threading.Event`` semantics)."""
    p = _current()
    if p is None:
        return _threading.Event()  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
    return p.event()


def thread(target: Optional[Callable] = None, *, name: Optional[str] = None,
           args: tuple = (), kwargs: Optional[dict] = None,
           daemon: Optional[bool] = None):
    """A thread handle (``threading.Thread`` call-shape subset the repo
    uses: target/name/args/kwargs/daemon keywords, ``start``/``join``/
    ``is_alive``/``name``/``daemon``)."""
    p = _current()
    if p is None:
        return _threading.Thread(  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
            target=target, name=name, args=args, kwargs=kwargs or {},
            daemon=daemon)
    return p.thread(target=target, name=name, args=args,
                    kwargs=kwargs or {}, daemon=daemon)


def fifo_queue(maxsize: int = 0):
    """A FIFO queue (``queue.Queue`` semantics, including the
    ``queue.Empty``/``queue.Full`` exceptions)."""
    p = _current()
    if p is None:
        return _queue_mod.Queue(maxsize)  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
    return p.fifo_queue(maxsize)


def sleep(seconds: float) -> None:
    """``time.sleep`` through the seam: a provider turns it into a pure
    scheduling point (no wall time passes under graftrace)."""
    p = _current()
    if p is None:
        _time.sleep(seconds)  # graftlint: ignore[raw-concurrency-primitive] -- the seam itself
        return
    p.sleep(seconds)
