# Runnable encodings of the project's standard invocations (tox.ini holds
# the same recipes for environments with tox installed; this image bakes
# in make but not tox). `make test` reproduces the full suite exactly as
# CI/judging runs it (-m "not slow", matching the tier-1 verify; run
# `pytest tests/ -q -m slow` for the excluded long-running set).

PY ?= python
TEST_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test examples bench dryrun telemetry-check chaos-check perf-check \
	analysis-check supervise-check audit-check build-check race-check \
	batch-check ring-check scope-check serve-check query-check quake-check \
	sight-check churn-check mem-check dur-check

test:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m "not slow"

examples:
	$(TEST_ENV) $(PY) -m pytest tests/test_examples.py -q

# Telemetry plane: the dedicated test subset plus a ~5 s live sockets demo
# that scrapes its own Prometheus endpoint over HTTP (tox env "telemetry").
telemetry-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_telemetry.py -q
	$(TEST_ENV) $(PY) examples/telemetry_demo.py

# Chaos plane: the full chaos test subset — slow-marked partition-heal soak
# included — plus the reconnect/quarantine recovery tests and a live 4-node
# demo walking the fault menu (tox env "chaos").
chaos-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_chaos.py tests/test_phi.py -q
	$(TEST_ENV) $(PY) examples/chaos_demo.py

# Frontier fast path + bit-packed state: the full equivalence sweep
# (frontier ≡ dense, bitset ≡ bool, donation, slow-marked edge-gather
# bench included) plus a small-n smoke of the bench 1M stage on the CPU
# backend — proves the frontier method column and its occupancy
# attribution end to end (tox env "perf").
perf-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_frontier.py -q
	$(TEST_ENV) BENCH_N_1M=4000 BENCH_CACHE=0 BENCH_TELEMETRY_DIR=/tmp \
		$(PY) bench.py --stage 1m

# Supervised execution plane: watchdog/store/crash-recovery tests (the
# slow-marked double-SIGKILL subprocess soak included) plus a live demo
# that preempts a PRNG-dependent run twice, corrupts a checkpoint, and
# proves bit-identical resume (tox env "supervise").
supervise-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_supervise.py -q
	$(TEST_ENV) $(PY) examples/supervised_run_demo.py

# graftlint + graftaudit gates: zero non-baselined findings at BOTH
# layers — source AST (retrace/host-sync/lock discipline) and compiled IR
# (jaxpr rules, signature parity, donation aliasing, cost ratchet, AND
# the graftmem memory ratchet/model-drift gate, which rides the full
# graftaudit run by default) — then both test subsets (tox env
# "analysis").
analysis-check:
	$(PY) -m p2pnetwork_tpu.analysis p2pnetwork_tpu/
	$(PY) -m p2pnetwork_tpu.analysis.ir
	$(TEST_ENV) $(PY) -m pytest tests/test_analysis.py -q

# graftaudit gate alone: the device-free IR audit over the full lowering
# registry (the CLI pins JAX_PLATFORMS=cpu + the 8-device virtual mesh
# itself), then its test subset — rule fixtures, parity gate, donation
# audit, budgets round-trip/ratchet (tox env "audit").
audit-check:
	$(PY) -m p2pnetwork_tpu.analysis.ir
	$(TEST_ENV) $(PY) -m pytest tests/test_iraudit.py -q

# graftmem static memory plane: the full graftaudit gate (the
# membudgets ratchet + analytic/compiled model-drift check ride it by
# default), the north-star capacity plan evaluated from the checked-in
# coefficients (fails loudly when membudgets.json lacks a capacity
# model), then the graftmem test subset — liveness-walk parity, ratchet
# arithmetic, planner extrapolation, the SimService hbm_budget_bytes
# 429 gate (tox env "mem").
mem-check:
	$(PY) -m p2pnetwork_tpu.analysis.ir
	$(PY) -m p2pnetwork_tpu.analysis.ir --plan
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m mem

# graftrace gate: the deterministic-concurrency scenario battery (every
# builtin scenario × K seeded schedules, zero non-baselined races or
# deadlocks) plus its test subset — scheduler replay determinism, the
# racy/clean twin per HB edge kind, detector internals, CLI exit codes
# (tox env "race").
race-check:
	$(TEST_ENV) $(PY) -m p2pnetwork_tpu.analysis.race
	$(TEST_ENV) $(PY) -m pytest tests/test_graftrace.py -q

# Incremental builds + IO-aware layouts: delta/rebuild bit-identity
# property sweep (native + numpy fallback), reorder-pass parity, layout
# cache, and the CI perf ratchet — a 1%-edge delta at 1M-edge scale must
# beat the from-scratch rebuild >= 10x on CPU (ratio-based, no
# wall-clock thresholds, no TPU; tox env "buildperf").
build-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_layout_delta.py -q
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m buildperf

# Batched message plane: lane-packed kernel parity, MessageBatch
# lifecycle (admission/retire/freeze), batched-vs-sequential bit
# identity, donation, and the slow-marked B=1024 aggregate-throughput
# ratchet (>= 20x vs sequential single-message runs, ratio-based on
# CPU; tox env "batch").
batch-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_messagebatch.py -q

# Comm seam: the ppermute vs Pallas ring-DMA halo backends must be
# bit-identical on every sharded protocol (interpret mode on the
# 8-device virtual CPU mesh), the lane-word batched path included, and
# the ICI accounting must price the DMA hops like the ppermute hops
# they replace (tox env "ring").
ring-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_ring.py -q

# graftscope observability plane: flight-recorder bit-parity across
# engine/batch/sharded (both comm backends), trace-plane span trees +
# Perfetto export schema, history ring + /history endpoint, and the
# probe_log / profiler satellites (tox env "scope"; the slow-marked
# 1.10x overhead ratchet runs with -m 'scope and slow').
scope-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_graftscope.py -q

# graftserve serving plane: submit/poll/stream lifecycle, admission
# pacing + quotas + structured load shedding, seeded-traffic
# determinism, preempt/resume bit-identity, and the HTTP endpoints
# riding the telemetry httpd (tox env "serve"; the slow-marked
# 1k-concurrent-lane 100k-node soak runs with -m 'serve and slow').
serve-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_serve.py -q

# graftquake device-plane chaos: seeded halo-hop fault injection
# (byte-replayable, chunked == unchunked via fault_round0, bit-identical
# across both comm backends), one-shot chip-loss/wedge dispatch faults,
# integrity checks + RetryPolicy/Healer recovery bit-identity across
# engine/sharded/graftserve, and the store/bench satellites (tox env
# "quake"; the slow-marked 100k chaos soak + 1.10x integrity-check
# overhead ratchet run with -m 'quake and slow').
quake-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_graftquake.py -q

# graftsight observability plane: ticket-scoped correlated tracing
# (one Perfetto tree per ticket lifecycle, chaos included), the
# tick-phase profiler + /dashboard endpoint, the SLO burn-rate engine
# and its AIMD admission consumption, and the tracer-on bit-identity
# pins (tox env "sight"; the slow-marked 1.10x serve-tick overhead
# ratchet runs with -m 'sight and slow').
sight-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_graftsight.py -q

# graftchurn live-growth plane: bit-identical overlay growth with the
# O(log K) geometric repad schedule, checkpoint/supervised resume
# across a repad, mid-service grow/delta mutations (zero admitted
# lanes dropped, untouched tickets bit-identical), sidecar growth
# replay, and seeded churn storms (tox env "churn"; the slow-marked
# 100k churn-under-chaos soak runs with -m 'churn and slow').
churn-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_graftchurn.py -q

# graftdur durability plane: write-ahead intent journal (CRC records,
# torn-tail fuzz at every byte offset, segment rotation/compaction),
# crash-seam resume bit-identity (mid-tick, mid-sidecar-publish,
# mid-journal-append), DurabilityLost shedding + HTTP 503s, hot-standby
# promote + FencedEpoch fencing (tox env "dur"; the slow-marked
# crash-storm campaign and the 1.10x fsync=tick overhead ratchet run
# with -m 'dur and slow').
dur-check:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -m dur

# Batched query lanes: byte-budget gate, lane-kernel parity, the three
# family identity sweeps (min-plus vs Bellman-Ford reference, DHT vs the
# numpy greedy walk, push-sum float-op-order vs models/pushsum.py), the
# query engine loop + observability pins (tox env "query"; the
# slow-marked 10x aggregate ratchets run with -m 'query and slow').
query-check:
	$(TEST_ENV) $(PY) -m pytest tests/test_querybatch.py -q

# North-star benchmark on the real TPU chip. bench.py probes the backend
# in a subprocess first and emits an error JSON instead of hanging when
# the device tunnel is wedged.
bench:
	$(PY) bench.py

# Compile-check the single-chip entry and the multi-chip sharded training
# step on an 8-device virtual mesh (what the driver validates).
dryrun:
	$(TEST_ENV) $(PY) -c "import __graft_entry__ as g; fn, args = g.entry(); fn(*args); g.dryrun_multichip(8); print('dryrun OK')"
