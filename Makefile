# Runnable encodings of the project's standard invocations (tox.ini holds
# the same recipes for environments with tox installed; this image bakes
# in make but not tox). `make test` reproduces the full suite exactly as
# CI/judging runs it.

PY ?= python
TEST_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test examples bench dryrun

test:
	$(TEST_ENV) $(PY) -m pytest tests/ -q

examples:
	$(TEST_ENV) $(PY) -m pytest tests/test_examples.py -q

# North-star benchmark on the real TPU chip. bench.py probes the backend
# in a subprocess first and emits an error JSON instead of hanging when
# the device tunnel is wedged.
bench:
	$(PY) bench.py

# Compile-check the single-chip entry and the multi-chip sharded training
# step on an 8-device virtual mesh (what the driver validates).
dryrun:
	$(TEST_ENV) $(PY) -c "import __graft_entry__ as g; fn, args = g.entry(); fn(*args); g.dryrun_multichip(8); print('dryrun OK')"
